"""Injectable network disruption schemes for the transport hubs.

Role model: the reference test framework's ``ServiceDisruptionScheme``
family (test/framework/.../test/disruption/): ``NetworkDisruption`` with
its ``NetworkDelay`` / ``NetworkDisconnect`` / ``NetworkUnresponsive``
link behaviors, ``SlowClusterStateProcessing``, and
``MockTransportService``'s per-action request blackholing.

A scheme is installed on a hub (``TransportHub`` or ``TcpTransportHub``)
with ``apply_to(hub)`` and applied to every delivery it matches:
``applies(src, dst, action)`` filters, ``disrupt(src, dst, action)``
executes the effect — sleep (delay), raise ``NodeNotConnectedException``
(drop/partition), or block until the caller's request deadline fires
(unresponsive/blackhole). Randomized schemes take an explicit ``seed`` so
disruption tests are reproducible.

Usage::

    drop = NetworkDrop(0.3, seed=7).apply_to(hub)
    delay = NetworkDelay(0.2).apply_to(hub)
    ...drive the cluster...
    drop.remove(); delay.remove()    # or hub.clear_disruptions()

Schemes compose: every installed scheme whose filter matches runs, in
installation order.
"""

from __future__ import annotations

import fnmatch
import random
import threading
from typing import Iterable, Optional, Sequence

from elasticsearch_tpu.common.errors import NodeNotConnectedException


class DisruptionScheme:
    """Base scheme: optional link/action filters + the disruption hook.

    ``src``/``dst``: restrict to deliveries from/to these node ids (None =
    any). ``nodes``: restrict to deliveries touching any of these nodes in
    either direction. ``actions``: fnmatch patterns over the action name
    (``internal:cluster/*``).
    """

    def __init__(self, src: Optional[Iterable[str]] = None,
                 dst: Optional[Iterable[str]] = None,
                 nodes: Optional[Iterable[str]] = None,
                 actions: Optional[Sequence[str]] = None):
        self.src = set(src) if src else None
        self.dst = set(dst) if dst else None
        self.nodes = set(nodes) if nodes else None
        self.actions = list(actions) if actions else None
        self.hub = None

    # --- lifecycle ----------------------------------------------------

    def apply_to(self, hub) -> "DisruptionScheme":
        hub.add_disruption(self)
        self.hub = hub
        return self

    def remove(self) -> None:
        if self.hub is not None:
            self.hub.remove_disruption(self)
            self.hub = None

    # --- matching + effect --------------------------------------------

    def applies(self, src: str, dst: str, action: str) -> bool:
        if self.src is not None and src not in self.src:
            return False
        if self.dst is not None and dst not in self.dst:
            return False
        if self.nodes is not None and not ({src, dst} & self.nodes):
            return False
        if self.actions is not None and not any(
                # exact match first: ES action names contain [s][r]
                # suffixes that fnmatch would treat as character classes
                action == pat or fnmatch.fnmatch(action, pat)
                for pat in self.actions):
            return False
        return True

    def disrupt(self, src: str, dst: str, action: str) -> None:
        """Effect hook; runs outside the hub lock. May sleep or raise."""
        raise NotImplementedError


class NetworkDelay(DisruptionScheme):
    """Fixed or uniformly-random per-delivery delay
    (NetworkDisruption.NetworkDelay)."""

    def __init__(self, seconds: float, max_seconds: Optional[float] = None,
                 seed: Optional[int] = None, **filters):
        super().__init__(**filters)
        self.seconds = float(seconds)
        self.max_seconds = float(max_seconds) if max_seconds else None
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()

    def delay(self) -> float:
        if self.max_seconds is None:
            return self.seconds
        with self._rng_lock:
            return self._rng.uniform(self.seconds, self.max_seconds)

    def disrupt(self, src, dst, action) -> None:
        import time

        time.sleep(self.delay())


class NetworkDrop(DisruptionScheme):
    """Probabilistic request drop: each matching delivery fails with
    probability ``p`` (connection-level error, so retry policies and
    failover engage). ``seed`` makes the drop sequence reproducible."""

    def __init__(self, p: float, seed: Optional[int] = None, **filters):
        super().__init__(**filters)
        if not 0.0 <= p <= 1.0:
            raise ValueError("drop probability must be in [0, 1]")
        self.p = float(p)
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self.dropped = 0

    def disrupt(self, src, dst, action) -> None:
        with self._rng_lock:
            hit = self._rng.random() < self.p
        if hit:
            self.dropped += 1
            raise NodeNotConnectedException(
                f"[{dst}] dropped [{action}] from [{src}] (injected)")


class NetworkPartition(DisruptionScheme):
    """Partition between two node sets (NetworkDisruption.Bridge /
    TwoPartitions). ``one_way=True`` drops only side1→side2 traffic —
    the asymmetric-partition case where a deposed master can still hear
    the cluster that can no longer hear it."""

    def __init__(self, side1: Iterable[str], side2: Iterable[str],
                 one_way: bool = False, **filters):
        super().__init__(**filters)
        self.side1 = set(side1)
        self.side2 = set(side2)
        self.one_way = bool(one_way)

    def disrupt(self, src, dst, action) -> None:
        forward = src in self.side1 and dst in self.side2
        backward = src in self.side2 and dst in self.side1
        if forward or (backward and not self.one_way):
            raise NodeNotConnectedException(
                f"[{dst}] partitioned from [{src}] (injected)")


class UnresponsiveNode(DisruptionScheme):
    """The node accepts requests but never answers
    (NetworkDisruption.NetworkUnresponsive): the delivery blocks until
    the caller's request timeout fires (or ``max_block_s`` as a leak
    guard), then fails. ``remove()``/``heal`` unblocks parked deliveries
    immediately."""

    def __init__(self, node: str, max_block_s: float = 60.0, **filters):
        filters.setdefault("nodes", [node])
        super().__init__(**filters)
        self.node = node
        self.max_block_s = float(max_block_s)
        self._healed = threading.Event()

    def remove(self) -> None:
        self._healed.set()
        super().remove()

    def disrupt(self, src, dst, action) -> None:
        self._healed.wait(self.max_block_s)
        raise NodeNotConnectedException(
            f"[{self.node}] unresponsive, [{action}] never answered "
            f"(injected)")


# ---------------------------------------------------------------------------
# Shard-search disruption (query-path fault injection)
# ---------------------------------------------------------------------------
#
# Transport schemes above disrupt DELIVERIES between nodes; these disrupt
# the shard-local query phase itself (the reference's
# SearchService-level fault injection via MockSearchService /
# ShardSearchFailure tests). They install into a process-global registry
# consulted by ``ShardSearcher.query`` and the mesh plane ladder, so the
# single-node path — where shard execution is a method call, not an RPC —
# is injectable too.

_SEARCH_SCHEMES: list = []


class ShardSearchScheme:
    """Base for query-path schemes. ``indices``/``shards`` filter which
    (index, shard) executions the scheme touches (None = any)."""

    def __init__(self, indices: Optional[Iterable[str]] = None,
                 shards: Optional[Iterable[int]] = None):
        self.indices = set(indices) if indices else None
        self.shards = set(shards) if shards is not None else None
        self.hits = 0

    def install(self) -> "ShardSearchScheme":
        _SEARCH_SCHEMES.append(self)
        return self

    def remove(self) -> None:
        if self in _SEARCH_SCHEMES:
            _SEARCH_SCHEMES.remove(self)

    def applies(self, index: str, shard_id) -> bool:
        if self.indices is not None and index not in self.indices:
            return False
        if self.shards is not None and shard_id not in self.shards:
            return False
        return True

    def on_search(self, index: str, shard_id: int) -> None:
        """Effect hook for the per-shard query phase."""

    def on_plane(self, index: str, plane: str) -> None:
        """Effect hook for a mesh execution plane (mesh_pallas / mesh)."""

    def on_staging(self, index: str, kind: str, table: str) -> None:
        """Effect hook for a device STAGING boundary (ISSUE 10): called
        right before each staging site's device transfer group, with the
        accountant kind (postings_raw/postings_packed/live_mask/
        embeddings/mesh_slot_tables/doc_values) and the table name — an
        injected raise here is indistinguishable from a ``device_put``
        fault mid-sequence."""

    def on_launch(self, index: str, rung: str) -> None:
        """Effect hook for a kernel LAUNCH, per rung (mesh_pallas /
        batched / pruned / knn) — finer-grained than ``on_plane``, which
        fires once per plane attempt before any staging."""

    def on_query(self, index: str) -> None:
        """Effect hook at query dispatch (before any plane/shard work) —
        the EvictionStormScheme's consult point."""


def clear_search_disruptions() -> None:
    del _SEARCH_SCHEMES[:]


def on_shard_search(index: str, shard_id: int) -> None:
    """Called by ShardSearcher.query before segment execution; runs every
    installed matching scheme in installation order."""
    if not _SEARCH_SCHEMES:
        return
    for scheme in list(_SEARCH_SCHEMES):
        if scheme.applies(index, shard_id):
            scheme.on_search(index, shard_id)


def on_plane_execute(index: str, plane: str) -> None:
    """Called by the mesh plane ladder right before executing on a plane
    (``plane`` in {"mesh_pallas", "mesh"}) — an injected raise here is
    indistinguishable from a compile/runtime fault on that plane."""
    if not _SEARCH_SCHEMES:
        return
    for scheme in list(_SEARCH_SCHEMES):
        # shard filters don't apply: the mesh plane executes ALL shards
        # as one program
        if scheme.indices is None or index in scheme.indices:
            scheme.on_plane(index, plane)


def on_device_staging(index: str, kind: str, table: str) -> None:
    """Called by every device staging site (Segment cold builds,
    MeshPlanExecutor.ensure_kernel/ensure_knn, doc-value columns)
    immediately before its device transfer group; runs inside the
    site's retry loop so a retried attempt re-consults the schemes."""
    if not _SEARCH_SCHEMES:
        return
    for scheme in list(_SEARCH_SCHEMES):
        if scheme.indices is None or index in scheme.indices:
            scheme.on_staging(index, kind, table)


def on_kernel_launch(index: str, rung: str) -> None:
    """Called right before each compiled-program launch, with the rung
    actually launching (``mesh_pallas`` serial / ``mesh`` scatter /
    ``batched`` / ``pruned`` / ``knn``) — an injected raise here lands
    in the plane ladder's fault handler (quarantine, next rung)."""
    if not _SEARCH_SCHEMES:
        return
    for scheme in list(_SEARCH_SCHEMES):
        if scheme.indices is None or index in scheme.indices:
            scheme.on_launch(index, rung)


def on_query_begin(index: str) -> None:
    """Called once per search dispatch (IndexService)."""
    if not _SEARCH_SCHEMES:
        return
    for scheme in list(_SEARCH_SCHEMES):
        if scheme.indices is None or index in scheme.indices:
            scheme.on_query(index)


class SearchDelayScheme(ShardSearchScheme):
    """Every matching shard search stalls ``seconds`` before executing —
    the straggler-shard generator for timeout/cancellation tests (the
    `timeout=50ms` acceptance path)."""

    def __init__(self, seconds: float, **filters):
        super().__init__(**filters)
        self.seconds = float(seconds)

    def on_search(self, index, shard_id) -> None:
        import time

        self.hits += 1
        time.sleep(self.seconds)


class SearchFailScheme(ShardSearchScheme):
    """Every matching shard search raises (a per-shard query-phase
    exception — must become a failures[] entry + _shards.failed, never a
    500, unless allow_partial_search_results=false)."""

    def __init__(self, exception: Optional[Exception] = None, **filters):
        super().__init__(**filters)
        self.exception = exception

    def on_search(self, index, shard_id) -> None:
        self.hits += 1
        if self.exception is not None:
            raise self.exception
        raise RuntimeError(
            f"[{index}][{shard_id}] query phase failed (injected)")


class PlaneFailScheme(ShardSearchScheme):
    """An execution plane of the mesh ladder raises on use: the analog of
    a Pallas compile failure / device OOM. ``planes``: which rungs fault
    ("mesh_pallas", "mesh"). Drives the plane-health quarantine."""

    def __init__(self, planes: Sequence[str] = ("mesh_pallas",), **filters):
        super().__init__(**filters)
        self.planes = set(planes)

    def on_plane(self, index, plane) -> None:
        if plane in self.planes:
            self.hits += 1
            raise RuntimeError(
                f"[{index}] plane [{plane}] fault (injected)")


class StagingFailScheme(ShardSearchScheme):
    """A device STAGING boundary faults (ISSUE 10): the Nth matching
    device transfer inside ``ensure_kernel`` / ``ensure_knn`` /
    ``Segment._stage_kernel_arrays`` / doc-value column staging raises,
    selectable by ledger kind and by error class.

    ``kinds``: accountant kinds to match (``postings`` matches both
    ``postings_raw`` and ``postings_packed``); None = any.
    ``nth``: skip the first nth-1 matching staging calls.
    ``times``: raise on at most this many calls, then go inert (None =
    every matching call while installed) — ``times=1`` with
    ``transient=True`` is the "one transient RESOURCE_EXHAUSTED, then
    clean" shape the bounded-retry path must absorb.
    ``transient``: raise :class:`TransientDeviceError` (retryable);
    False raises ``ValueError`` (deterministic — immediate demotion +
    quarantine, never retried).
    """

    def __init__(self, kinds=None, nth: int = 1,
                 times: Optional[int] = None, transient: bool = True,
                 **filters):
        super().__init__(**filters)
        self.kinds = set(kinds) if kinds else None
        self.nth = max(1, int(nth))
        self.times = times
        self.transient = bool(transient)
        self.calls = 0
        self._lock = threading.Lock()

    def _kind_matches(self, kind: str) -> bool:
        if self.kinds is None:
            return True
        return kind in self.kinds or (
            "postings" in self.kinds and kind.startswith("postings"))

    def on_staging(self, index, kind, table) -> None:
        if not self._kind_matches(kind):
            return
        with self._lock:
            self.calls += 1
            if self.calls < self.nth:
                return
            if self.times is not None and self.hits >= self.times:
                return
            self.hits += 1
        if self.transient:
            from elasticsearch_tpu.common.staging import (
                TransientDeviceError,
            )

            raise TransientDeviceError(
                f"[{index}] RESOURCE_EXHAUSTED staging [{kind}/{table}] "
                f"(injected transient)")
        raise ValueError(
            f"[{index}] shape error staging [{kind}/{table}] "
            f"(injected deterministic)")


class KernelLaunchFailScheme(ShardSearchScheme):
    """A compiled-program LAUNCH faults, per rung: ``mesh_pallas``
    (serial kernel plane), ``mesh`` (scatter), ``batched``, ``pruned``,
    ``knn``. Lands in the plane ladder's fault handler — quarantine
    once, serve from the next rung. ``times``: at most N raises, then
    inert (None = always while installed)."""

    def __init__(self, rungs: Sequence[str] = ("mesh_pallas",),
                 times: Optional[int] = None, **filters):
        super().__init__(**filters)
        self.rungs = set(rungs)
        self.times = times
        self._lock = threading.Lock()

    def on_launch(self, index, rung) -> None:
        if rung not in self.rungs:
            return
        with self._lock:
            if self.times is not None and self.hits >= self.times:
                return
            self.hits += 1
        raise RuntimeError(
            f"[{index}] kernel launch [{rung}] fault (injected)")


class EvictionStormScheme(ShardSearchScheme):
    """Force the DeviceMemoryAccountant's LRU evictor under query load:
    every ``period``-th matching query dispatch evicts the ``scopes``
    coldest evictable staging scopes, driving the restage-under-pressure
    paths (lazy restage, ``probe`` lifecycle events, ladder demotions)
    without configuring a byte budget."""

    def __init__(self, period: int = 1, scopes: int = 1, **filters):
        super().__init__(**filters)
        self.period = max(1, int(period))
        self.scopes = max(1, int(scopes))
        self.evicted_bytes = 0
        self.calls = 0
        self._lock = threading.Lock()

    def on_query(self, index) -> None:
        with self._lock:
            self.calls += 1
            if self.calls % self.period:
                return
            self.hits += 1
        from elasticsearch_tpu.common.memory import memory_accountant

        freed = memory_accountant().force_evict(self.scopes)
        with self._lock:  # concurrent searchers must not lose updates
            self.evicted_bytes += freed


class QueuePressureScheme(ShardSearchScheme):
    """Synthetic pressure on the search ADMISSION plane (ISSUE 12,
    docs/OVERLOAD.md): the overload analog of the staging/launch fault
    schemes. Consulted by ``SearchAdmissionController`` at every
    acquire/release:

    ``occupancy``: synthetic queued entries pinned onto the admission
    queue — raises queue pressure (driving the brownout ladder) and
    counts toward the overflow check, so ``occupancy >= search.queue
    .size`` forces every arrival that cannot take a free slot into a
    clean 429.
    ``block_slots``: concurrency slots withheld from the controller's
    ``max_concurrent`` — arrivals queue (and drain by DRR) as if that
    much capacity were busy elsewhere.
    ``drain_delay_s``: added to every release, slowing the observed
    drain rate (stretches the computed Retry-After).
    """

    def __init__(self, occupancy: int = 0, block_slots: int = 0,
                 drain_delay_s: float = 0.0, **filters):
        super().__init__(**filters)
        self.occupancy = max(0, int(occupancy))
        self.block_slots = max(0, int(block_slots))
        self.drain_delay_s = float(drain_delay_s)


def queue_pressure(index: str, count_hit: bool = True):
    """(occupancy, blocked_slots, drain_delay_s) summed over the
    installed matching :class:`QueuePressureScheme`s. ``count_hit``:
    admission's acquire consults count as scheme hits; bookkeeping
    consults (level refresh, window sizing) do not."""
    if not _SEARCH_SCHEMES:
        return 0, 0, 0.0
    occ = blocked = 0
    delay = 0.0
    for scheme in list(_SEARCH_SCHEMES):
        if not isinstance(scheme, QueuePressureScheme):
            continue
        if scheme.indices is not None and index not in scheme.indices:
            continue
        if count_hit:
            scheme.hits += 1
        occ += scheme.occupancy
        blocked += scheme.block_slots
        delay = max(delay, scheme.drain_delay_s)
    return occ, blocked, delay


# ---------------------------------------------------------------------------
# Store corruption (data-integrity fault injection, ISSUE 16)
# ---------------------------------------------------------------------------


class StoreCorruptionScheme:
    """Deterministic at-rest / in-flight store corruption injector
    (ISSUE 16; the reference's ``CorruptionUtils`` used by
    ``CorruptedFileIT``). Every injected corruption MUST be detected —
    the chaos soak's zero-silent-wrong-results assertion — so each
    injection is logged in ``self.corrupted``.

    Kinds:

    - ``bitflip``: flip one bit of one byte of a checksummed data file
      (``target`` names it, default ``arrays.npz`` — the chosen array);
    - ``truncate``: cut the tail byte off a data file (short read);
    - ``torn_checksums``: truncate ``checksums.json`` mid-JSON (the
      verification metadata itself is damaged);
    - ``missing_checksums``: delete ``checksums.json`` outright.

    At rest: ``corrupt_store(store)`` / ``corrupt_segment(dir)`` mutate
    committed files directly — the next load / scrub / recovery-source
    walk must catch it. In flight ("during recovery"): install on a hub
    with ``source_node`` set and the scheme flips a byte inside the
    source's in-memory recovery-session snapshot on the first matching
    file-chunk delivery — the bytes no longer match the manifest digest
    the source computed, so the TARGET's install verification must
    catch it (and the retried session, re-read from clean disk, heals).

    ``seed`` makes the chosen file/byte/bit reproducible.
    """

    KINDS = ("bitflip", "truncate", "torn_checksums", "missing_checksums")

    def __init__(self, kind: str = "bitflip",
                 target: Optional[str] = None,
                 seed: Optional[int] = None,
                 source_node=None, times: int = 1):
        if kind not in self.KINDS:
            raise ValueError(f"unknown corruption kind [{kind}]")
        self.kind = kind
        self.target = target
        self.source_node = source_node
        self.times = max(1, int(times))
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.hub = None
        self.hits = 0
        self.corrupted: list = []  # (path, description) per injection

    # --- at-rest ------------------------------------------------------

    def corrupt_segment(self, seg_dir: str) -> str:
        """Corrupt one file inside a sealed segment directory; returns
        the path corrupted. Deterministic under ``seed``."""
        import json as _json
        import os

        sums_path = os.path.join(seg_dir, "checksums.json")
        if self.kind == "missing_checksums":
            os.remove(sums_path)
            self._log(sums_path, "deleted checksums.json")
            return sums_path
        if self.kind == "torn_checksums":
            size = os.path.getsize(sums_path)
            with open(sums_path, "r+b") as f:
                f.truncate(max(1, size // 2))  # mid-JSON tear
            self._log(sums_path, "tore checksums.json")
            return sums_path
        with open(sums_path, encoding="utf-8") as f:
            names = sorted(_json.load(f))
        if not names:
            raise ValueError(f"segment [{seg_dir}] has no checksummed files")
        if self.target is not None:
            if self.target not in names:
                raise ValueError(
                    f"target [{self.target}] not checksummed in [{seg_dir}]")
            name = self.target
        else:
            name = ("arrays.npz" if "arrays.npz" in names
                    else self._rng.choice(names))
        path = os.path.join(seg_dir, name)
        if self.kind == "truncate":
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(max(0, size - 1))
            self._log(path, "truncated 1 byte")
            return path
        # bitflip
        size = os.path.getsize(path)
        offset = self._rng.randrange(max(1, size))
        bit = 1 << self._rng.randrange(8)
        with open(path, "r+b") as f:
            f.seek(offset)
            byte = f.read(1)
            f.seek(offset)
            f.write(bytes([byte[0] ^ bit]))
        self._log(path, f"flipped bit {bit:#04x} at offset {offset}")
        return path

    def corrupt_store(self, store, segment: Optional[str] = None) -> str:
        """Corrupt one committed segment of ``store`` (the newest by
        default) — the at-rest entry point for shard-level tests."""
        commit = store.read_commit() or {}
        names = [s["name"] if isinstance(s, dict) else s
                 for s in commit.get("segments", [])]
        if not names:
            raise ValueError("store has no committed segments to corrupt")
        name = segment if segment is not None else names[-1]
        return self.corrupt_segment(store._seg_dir(name))

    def _log(self, path: str, what: str) -> None:
        with self._lock:
            self.hits += 1
            self.corrupted.append((path, f"{self.kind}: {what}"))

    # --- in-flight (recovery stream) ----------------------------------
    #
    # Duck-types the DisruptionScheme hub protocol (apply_to / applies /
    # disrupt) instead of subclassing: the effect is a payload mutation
    # on the SOURCE, not a delivery failure, so none of the base class's
    # raise/sleep semantics apply.

    def apply_to(self, hub) -> "StoreCorruptionScheme":
        if self.source_node is None:
            raise ValueError(
                "in-flight corruption needs source_node (the recovery "
                "source's MultiNodeService)")
        hub.add_disruption(self)
        self.hub = hub
        return self

    def remove(self) -> None:
        if self.hub is not None:
            self.hub.remove_disruption(self)
            self.hub = None

    def applies(self, src: str, dst: str, action: str) -> bool:
        return (self.source_node is not None
                and action == "internal:index/shard/recovery/files/chunk"
                and dst == self.source_node.node_id)

    def disrupt(self, src: str, dst: str, action: str) -> None:
        """Flip one bit inside every open recovery session's snapshot on
        the source — AFTER the manifest digests were computed, so the
        shipped bytes can no longer verify. Fires ``times`` deliveries,
        then goes inert (the retried session re-reads clean disk)."""
        with self._lock:
            if self.hits >= self.times:
                return
            sessions = getattr(self.source_node, "_recovery_sessions", {})
            flipped = False
            for sess in sessions.values():
                for rel in sorted(sess.get("files", {})):
                    data = sess["files"][rel]
                    if not data:
                        continue
                    offset = self._rng.randrange(len(data))
                    bit = 1 << self._rng.randrange(8)
                    sess["files"][rel] = (data[:offset]
                                          + bytes([data[offset] ^ bit])
                                          + data[offset + 1:])
                    self.hits += 1
                    self.corrupted.append(
                        (rel, f"in-flight bitflip at offset {offset}"))
                    flipped = True
                    break
                if flipped:
                    break


class ActionBlackhole(DisruptionScheme):
    """Requests matching the action patterns vanish: the delivery blocks
    until the caller's deadline (MockTransportService's request
    blackholing by action name). Scope with ``dst=[...]`` to blackhole a
    single replica's writes while the node otherwise stays reachable."""

    def __init__(self, actions: Sequence[str], max_block_s: float = 60.0,
                 **filters):
        super().__init__(actions=list(actions), **filters)
        self.max_block_s = float(max_block_s)
        self._healed = threading.Event()
        self.swallowed = 0

    def remove(self) -> None:
        self._healed.set()
        super().remove()

    def disrupt(self, src, dst, action) -> None:
        self.swallowed += 1
        self._healed.wait(self.max_block_s)
        raise NodeNotConnectedException(
            f"[{dst}] blackholed [{action}] from [{src}] (injected)")
