"""Runtime lock-order witness (ISSUE 15 — the dynamic half of pass 5).

Eraser-style confirmation of docs/LOCK_ORDER.md: while installed, every
``threading.Lock``/``threading.RLock`` CREATED from package code is
wrapped in a shim that records, per thread, which lock sites were held
when another site was acquired. At the end of a soak
``assert_acyclic()`` fails if two sites were ever acquired in both
orders — the observed-inversion signal static pass 5 approximates, but
instance-accurate and inclusive of the paths the static graph cannot
see (callback-mediated acquisition like the accountant's evict hooks,
dynamic dispatch, thread hops).

Scope and precision:

- Only locks whose creation frame lies inside ``elasticsearch_tpu``
  are instrumented; everything else (jax internals, stdlib Events
  created by library code) gets a raw lock — zero overhead off-package.
- Only locks CREATED while installed are observed. Module globals and
  process singletons that predate the install window (``
  _MESH_EXEC_LOCK``, the memory accountant's lock) would be invisible
  — ``wrap_central_locks()`` closes exactly that gap by swapping a
  shim over the live attribute (new acquisitions go through the shim,
  the shim delegates to the SAME inner lock, so mutual exclusion with
  any in-flight holder is preserved); ``uninstall()`` restores the
  originals. The evict-callback paths the static graph cannot see are
  observable only through these wrapped singletons.
- A site is the CREATION statement (``file:line``), one node per site
  regardless of how many instances it creates — matching the static
  graph's granularity.
- Same-site pairs (holding one instance of a site while acquiring
  another instance of the same site, e.g. peer nodes locking each
  other's engines) carry no order information at site granularity;
  they are reported (``same_site_nestings``) but excluded from the
  cycle assertion.
- Reentrant RLock re-acquisition by the owning thread records nothing.

Install via the ``lock_order_witness()`` context manager — the chaos
soaks (testing/chaos.py) run their whole body under it and fold
``report()`` into theirs; tests/test_contract_lint.py drives it
directly with deliberate inversions.
"""

from __future__ import annotations

import _thread
import os
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_THIS_FILE = os.path.abspath(__file__)


class LockOrderViolation(AssertionError):
    """Two lock sites were observed acquired in both orders."""


def _creation_site(skip_files: Tuple[str, ...]) -> Optional[str]:
    """``relpath:lineno`` of the first package frame below the factory,
    or None when the lock is created outside the package (frames inside
    threading.py — Condition/Event/Semaphore internals — are skipped so
    a ``threading.Event()`` in package code attributes to that code)."""
    frame = sys._getframe(2)
    while frame is not None:
        fname = os.path.abspath(frame.f_code.co_filename)
        if fname not in skip_files and not fname.endswith("threading.py"):
            if fname.startswith(_PKG_DIR + os.sep):
                rel = os.path.relpath(fname, _PKG_DIR).replace(os.sep, "/")
                return f"{rel}:{frame.f_lineno}"
            return None
        frame = frame.f_back
    return None


class _Held(threading.local):
    def __init__(self):
        self.stack: List[str] = []  # site per successful acquisition


class LockOrderWitness:
    """One observation session. Use via :func:`lock_order_witness`."""

    def __init__(self):
        self._reg_lock = _thread.allocate_lock()
        self._held = _Held()
        # (held site, acquired site) -> observation count
        self.pairs: Dict[Tuple[str, str], int] = {}
        self._installed = False
        self._orig_lock = None
        self._orig_rlock = None
        # (holder, attr, original) for wrap_existing restores
        self._wrapped: List[Tuple[object, str, object]] = []

    # -- bookkeeping (called from the shims) ---------------------------

    def _note_acquired(self, site: str) -> None:
        held = self._held.stack
        for h in held:
            key = (h, site)
            with self._reg_lock:
                self.pairs[key] = self.pairs.get(key, 0) + 1
        held.append(site)

    def _note_released(self, site: str) -> None:
        held = self._held.stack
        # remove the most recent matching acquisition; a release from a
        # thread that never acquired (cross-thread handoff) is ignored
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                return

    # -- install / uninstall -------------------------------------------

    def install(self) -> "LockOrderWitness":
        assert not self._installed
        self._orig_lock = threading.Lock
        self._orig_rlock = threading.RLock
        witness = self
        orig_lock, orig_rlock = self._orig_lock, self._orig_rlock

        def lock_factory():
            site = _creation_site((_THIS_FILE,))
            inner = orig_lock()
            return inner if site is None else _LockShim(witness, site,
                                                        inner)

        def rlock_factory():
            site = _creation_site((_THIS_FILE,))
            inner = orig_rlock()
            return inner if site is None else _RLockShim(witness, site,
                                                         inner)

        threading.Lock = lock_factory
        threading.RLock = rlock_factory
        self._installed = True
        return self

    def wrap_existing(self, holder: object, attr: str,
                      site: str) -> None:
        """Swap an ALREADY-CREATED lock attribute for an instrumented
        shim over the same inner lock (see module docstring: this is
        how locks predating the install window become observable).
        Restored by :meth:`uninstall`."""
        inner = getattr(holder, attr)
        if isinstance(inner, (_LockShim, _RLockShim)):
            return
        shim = (_RLockShim(self, site, inner)
                if hasattr(inner, "_is_owned")  # C RLock protocol
                else _LockShim(self, site, inner))
        self._wrapped.append((holder, attr, inner))
        setattr(holder, attr, shim)

    def wrap_central_locks(self) -> None:
        """Wrap the process singletons every soak cares about: the mesh
        execution lock (module global, created at import) and the
        device-memory accountant's lock (singleton, created on first
        use — the lock every evict callback runs under)."""
        from elasticsearch_tpu.common.memory import memory_accountant
        from elasticsearch_tpu.parallel import plan_exec

        self.wrap_existing(plan_exec, "_MESH_EXEC_LOCK",
                           "parallel/plan_exec.py:_MESH_EXEC_LOCK")
        self.wrap_existing(memory_accountant(), "_lock",
                           "common/memory.py:DeviceMemoryAccountant."
                           "_lock")

    def uninstall(self) -> None:
        if self._installed:
            threading.Lock = self._orig_lock
            threading.RLock = self._orig_rlock
            self._installed = False
        while self._wrapped:
            holder, attr, inner = self._wrapped.pop()
            setattr(holder, attr, inner)

    # -- analysis -------------------------------------------------------

    def edges(self) -> Dict[Tuple[str, str], int]:
        with self._reg_lock:
            return {k: v for k, v in self.pairs.items() if k[0] != k[1]}

    def same_site_nestings(self) -> Dict[str, int]:
        with self._reg_lock:
            return {a: n for (a, b), n in self.pairs.items() if a == b}

    def find_cycle(self) -> Optional[List[str]]:
        """A cycle among distinct sites in the observed-order graph, or
        None. Any cycle here means two threads interleaving those code
        paths can deadlock."""
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges():
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        WHITE, GREY, BLACK = 0, 1, 2
        color = {v: WHITE for v in adj}
        parent: Dict[str, Optional[str]] = {}

        def dfs(v: str) -> Optional[List[str]]:
            color[v] = GREY
            for w in sorted(adj[v]):
                if color[w] == GREY:
                    cycle = [w, v]
                    p = parent.get(v)
                    while p is not None and p != w:
                        cycle.append(p)
                        p = parent.get(p)
                    cycle.reverse()
                    return cycle
                if color[w] == WHITE:
                    parent[w] = v
                    found = dfs(w)
                    if found:
                        return found
            color[v] = BLACK
            return None

        for v in sorted(adj):
            if color[v] == WHITE:
                parent[v] = None
                found = dfs(v)
                if found:
                    return found
        return None

    def assert_acyclic(self) -> None:
        cycle = self.find_cycle()
        if cycle is not None:
            raise LockOrderViolation(
                "lock sites acquired in conflicting orders (observed at "
                "runtime): " + " -> ".join(cycle) + f" -> {cycle[0]} — "
                "two threads interleaving these paths can deadlock; fix "
                "the ordering (docs/LOCK_ORDER.md) or split the lock")

    def report(self) -> dict:
        edges = self.edges()
        return {
            "instrumented_edges": len(edges),
            "observations": sum(edges.values()),
            "same_site_nestings": self.same_site_nestings(),
            "cycle": self.find_cycle(),
        }


class _LockShim:
    """threading.Lock lookalike recording acquisition order."""

    __slots__ = ("_witness", "_site", "_inner")

    def __init__(self, witness: LockOrderWitness, site: str, inner):
        self._witness = witness
        self._site = site
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._witness._note_acquired(self._site)
        return got

    def release(self) -> None:
        self._witness._note_released(self._site)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class _RLockShim:
    """threading.RLock lookalike; reentrant re-acquisition records no
    edge, and the ``_release_save``/``_acquire_restore``/``_is_owned``
    protocol keeps ``threading.Condition`` correct on top of it."""

    __slots__ = ("_witness", "_site", "_inner", "_count")

    def __init__(self, witness: LockOrderWitness, site: str, inner):
        self._witness = witness
        self._site = site
        self._inner = inner
        self._count = _Held()  # per-thread reentrancy depth

    def _depth(self) -> int:
        return len([s for s in self._count.stack if s == "d"])

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            if self._depth() == 0:
                self._witness._note_acquired(self._site)
            self._count.stack.append("d")
        return got

    def release(self) -> None:
        self._inner.release()
        if self._count.stack:
            self._count.stack.pop()
            if self._depth() == 0:
                self._witness._note_released(self._site)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition protocol (wait() must fully release a reentrant hold)
    def _release_save(self):
        depth = self._depth()
        self._count.stack = []
        if depth:
            self._witness._note_released(self._site)
        state = self._inner._release_save()
        return (state, depth)

    def _acquire_restore(self, saved) -> None:
        state, depth = saved
        self._inner._acquire_restore(state)
        if depth:
            self._witness._note_acquired(self._site)
        self._count.stack = ["d"] * depth

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


class lock_order_witness:
    """``with lock_order_witness() as w: ...; w.assert_acyclic()``"""

    def __init__(self):
        self.witness = LockOrderWitness()

    def __enter__(self) -> LockOrderWitness:
        return self.witness.install()

    def __exit__(self, *exc) -> None:
        self.witness.uninstall()
