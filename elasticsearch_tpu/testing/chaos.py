"""Deterministic chaos soak (ISSUE 10): every scheme family at once.

Role model: the reference's disruption ITs (§5.8 —
DiscoveryWithServiceDisruptionsIT, RecoveryWhileUnderLoadIT): drive real
concurrent load while injectable faults bite every layer, then assert
the standing invariants instead of scenario-specific outcomes. Here the
layers are the ones THIS system has: the transport hubs (PR 2 schemes),
the shard/plane query path (PR 4 schemes), and — new in this issue —
the device staging/launch boundary (StagingFailScheme /
KernelLaunchFailScheme / EvictionStormScheme).

``ChaosSoak`` composes all three families under concurrent bulk-ingest
and zipfian search on a packed multi-shard corpus, with a pinned seed so
every run injects the identical fault schedule. Invariants, checked
every round and at the end:

- **no acked-write loss** — every acked index/delete is visible after
  refresh, on the in-process index AND across the 2-node cluster with
  transport drops biting (replication retry + recovery compensate);
- **oracle-identical hits** — the disrupted index answers byte-identical
  (ids AND scores) to an undisrupted oracle index holding the same
  corpus: plane demotions degrade latency, never results (the chaos
  index pins ``index.search.mesh.plane: pallas`` so every rung on the
  ladder — mesh_pallas or host — shares the byte-identity contract);
- **ledger leak-free** — the per-kind device-memory ledger returns
  EXACTLY to its pre-fault snapshot after scheme removal plus one
  healing query (a mid-staging fault strands no orphaned HBM bytes);
- **restage amplification bounded** — storms of forced evictions may
  restage, but the restaged/logically-changed ratio stays under the
  configured bound;
- **zero 5xx while any copy survives** — no search raises and no shard
  fails on the in-process path; the cluster path always converges to a
  complete answer.

The tier-1 smoke runs a small seeded soak; the full soak (more rounds,
heavier drop rates) is slow-marked. ``dryrun_multichip`` phase 8 runs
the device-scheme subset against the real mesh.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.testing import disruption as dis

# the device/search scheme families the soak cycles through, one entry
# per round (modulo): (constructor name, kwargs builder) — deterministic
# in the round index, no RNG involved in the schedule itself
_ROUND_SCHEMES = (
    ("staging_transient", lambda idx: dis.StagingFailScheme(
        kinds=["postings"], transient=True, times=2, indices=[idx])),
    ("launch_fail", lambda idx: dis.KernelLaunchFailScheme(
        rungs=("mesh_pallas", "batched"), times=1, indices=[idx])),
    ("eviction_storm", lambda idx: dis.EvictionStormScheme(
        period=3, indices=[idx])),
    ("staging_transient_live", lambda idx: dis.StagingFailScheme(
        kinds=["live_mask"], transient=True, times=1, indices=[idx])),
    ("staging_deterministic_mesh", lambda idx: dis.StagingFailScheme(
        kinds=["mesh_slot_tables"], transient=False, times=1,
        indices=[idx])),
)

# tight transport deadlines so injected drops resolve in test time
_CLUSTER_SETTINGS = Settings({
    "transport.request.timeout": "3s",
    "transport.retry.max_attempts": 4,
    "transport.retry.initial_backoff": "20ms",
    "transport.retry.max_backoff": "200ms",
    "discovery.zen.publish_timeout": "2s",
    "cluster.replication.timeout": "600ms",
    "indices.recovery.retry_delay_network": "20ms",
    "indices.recovery.internal_action_timeout": "2s",
})


class ChaosSoakViolation(AssertionError):
    """One of the standing invariants failed under the soak."""


def _run_witnessed(body) -> dict:
    """Run a soak body under the runtime lock-order witness (ISSUE 15,
    docs/LOCK_ORDER.md): install the instrumented-lock factories, wrap
    the central singletons that predate the install window (the mesh
    execution lock, the accountant's evict-path lock), fold the
    observation report into the soak's (``lock_witness`` key), and
    convert an observed order inversion into the soak's own violation
    type. ``body`` returns the report dict."""
    from elasticsearch_tpu.testing.lockwitness import (
        LockOrderViolation,
        lock_order_witness,
    )

    with lock_order_witness() as witness:
        witness.wrap_central_locks()
        report = body()
    report["lock_witness"] = witness.report()
    try:
        witness.assert_acyclic()
    except LockOrderViolation as e:
        raise ChaosSoakViolation(str(e)) from e
    return report


class ChaosSoak:
    def __init__(self, seed: int = 0, rounds: int = 2,
                 docs_per_round: int = 24, searches_per_round: int = 6,
                 search_threads: int = 2, shards: int = 3,
                 seed_docs: int = 48, with_cluster: bool = True,
                 with_overload: bool = True,
                 cluster_drop_p: float = 0.15,
                 amplification_bound: float = 200.0,
                 quarantine_cooldown: str = "150ms",
                 index: str = "chaos"):
        self.seed = int(seed)
        self.rounds = int(rounds)
        self.docs_per_round = int(docs_per_round)
        self.searches_per_round = int(searches_per_round)
        self.search_threads = int(search_threads)
        self.shards = int(shards)
        self.seed_docs = int(seed_docs)
        self.with_cluster = bool(with_cluster)
        self.with_overload = bool(with_overload)
        # the overload phase's admission shape: one effective slot
        # (max_concurrent - block_slots), a small bounded queue, and
        # pinned synthetic occupancy at capacity so every arrival that
        # cannot take the free slot gets a clean 429 (docs/OVERLOAD.md)
        self.overload_queue_size = 8
        self.overload_max_concurrent = 2
        self.cluster_drop_p = float(cluster_drop_p)
        self.amplification_bound = float(amplification_bound)
        self.quarantine_cooldown = quarantine_cooldown
        self.index = index
        self.oracle_index = index + "_oracle"
        self.vocab = [f"w{i}" for i in range(16)]

    # -- deterministic inputs -------------------------------------------

    def schedule(self) -> List[List[str]]:
        """Per-round scheme names — pure function of (seed, rounds), so
        two soaks with the same seed inject identically."""
        rng = random.Random(self.seed)
        plan = []
        for r in range(self.rounds):
            base = _ROUND_SCHEMES[r % len(_ROUND_SCHEMES)][0]
            extra = _ROUND_SCHEMES[rng.randrange(len(_ROUND_SCHEMES))][0]
            # search-plane family (PR 4) rides every round
            plan.append(sorted({base, extra}) + ["search_delay"])
        return plan

    def _schemes_for(self, names: List[str]) -> List:
        by_name = dict(_ROUND_SCHEMES)
        schemes = []
        for name in names:
            if name == "search_delay":
                schemes.append(dis.SearchDelayScheme(
                    0.002, indices=[self.index]))
            else:
                schemes.append(by_name[name](self.index))
        return schemes

    def _doc(self, rng: np.random.RandomState, d: int) -> dict:
        n_toks = 3 + int(rng.randint(6))
        toks = [self.vocab[self._zipf_term(rng)] for _ in range(n_toks)]
        return {"body": " ".join(toks), "n": int(d)}

    def _zipf_term(self, rng: np.random.RandomState) -> int:
        return min(int(rng.zipf(1.4)) - 1, len(self.vocab) - 1)

    def _query(self, rng: np.random.RandomState) -> dict:
        terms = " ".join(self.vocab[self._zipf_term(rng)]
                         for _ in range(1 + int(rng.randint(2))))
        return {"query": {"match": {"body": terms}}, "size": 10}

    # -- targets ---------------------------------------------------------

    def _mk_index(self, name: str, overload: bool = False):
        from elasticsearch_tpu.index.index_service import IndexService

        settings = {
            "index.number_of_shards": self.shards,
            "index.search.mesh": True,
            # kernel-or-host ladder: every rung shares the byte-identity
            # contract (the scatter mesh is a different formulation)
            "index.search.mesh.plane": "pallas",
            "index.search.plane_quarantine.cooldown":
                self.quarantine_cooldown,
            "index.refresh_interval": -1,
        }
        if overload:
            # tight admission shape so the QueuePressureScheme phase
            # exercises real rejections (the oracle stays unbounded)
            settings["search.queue.size"] = self.overload_queue_size
            settings["search.admission.max_concurrent"] = \
                self.overload_max_concurrent
        return IndexService(name, Settings(settings),
                            mapping={"properties": {
                                "body": {"type": "text",
                                         "analyzer": "whitespace"},
                                "n": {"type": "integer"},
                            }})

    # -- invariant helpers ----------------------------------------------

    @staticmethod
    def _hits_key(resp) -> list:
        return [(h["_id"], h["_score"]) for h in resp["hits"]["hits"]]

    def _assert_parity(self, svc, oracle, bodies: List[dict],
                       report: dict) -> None:
        for body in bodies:
            got = svc.search(dict(body))
            want = oracle.search(dict(body))
            if got["_shards"]["failed"]:
                raise ChaosSoakViolation(
                    f"shard failures on the disrupted index: "
                    f"{got['_shards']}")
            # hit ids AND scores are byte-identical under every
            # degradation mode; TOTALS are only comparable outside
            # brownout (forced pruning reports a documented gte lower
            # bound — docs/OVERLOAD.md / docs/PRUNING.md)
            exact_total = not (got.get("_pruned") or got.get("_degraded"))
            if (exact_total
                    and got["hits"]["total"] != want["hits"]["total"]) or \
                    self._hits_key(got) != self._hits_key(want):
                raise ChaosSoakViolation(
                    f"hits diverged from the undisrupted oracle for "
                    f"{body!r}:\n got[{got['_plane']}]: "
                    f"{self._hits_key(got)}\nwant[{want['_plane']}]: "
                    f"{self._hits_key(want)}")
            report["parity_checked"] += 1
            report["planes_seen"].add(got["_plane"])

    @staticmethod
    def _kind_bytes(index_name: str) -> Dict[str, int]:
        from elasticsearch_tpu.common.memory import memory_accountant

        return memory_accountant().staged_bytes_by_kind(index_name)

    # -- the soak --------------------------------------------------------

    def run(self) -> dict:
        """Run the soak; returns the report dict or raises
        :class:`ChaosSoakViolation` with the first broken invariant.

        The whole soak executes under the runtime lock-order witness
        (ISSUE 15, docs/LOCK_ORDER.md): every package lock created
        during the run — plus the wrapped central singletons — records
        its per-thread acquisition order, and an observed order
        INVERSION — the dynamic form of the static pass-5 cycle —
        fails the soak like any other invariant."""
        return _run_witnessed(self._run_soak)

    def _run_soak(self) -> dict:
        report: dict = {
            "seed": self.seed, "rounds": self.rounds,
            "schedule": self.schedule(),
            "acked_writes": 0, "acked_deletes": 0,
            "searches_under_fault": 0, "search_errors": [],
            "parity_checked": 0, "planes_seen": set(),
            "scheme_hits": {}, "cluster": None, "overload": None,
        }
        rng = np.random.RandomState(self.seed)
        svc = self._mk_index(self.index, overload=self.with_overload)
        oracle = self._mk_index(self.oracle_index)
        cluster = None
        try:
            # seed corpus + warm the fast plane on both indices
            doc_id = 0
            live_ids: List[str] = []
            for _ in range(self.seed_docs):
                doc = self._doc(rng, doc_id)
                svc.index_doc(str(doc_id), doc)
                oracle.index_doc(str(doc_id), doc)
                live_ids.append(str(doc_id))
                doc_id += 1
            svc.refresh()
            oracle.refresh()
            warm_body = {"query": {"match": {"body": self.vocab[0]}},
                         "size": 10}
            svc.search(dict(warm_body))
            oracle.search(dict(warm_body))

            if self.with_cluster:
                cluster = self._start_cluster()

            for rnd, names in enumerate(report["schedule"]):
                schemes = self._schemes_for(names)
                for s in schemes:
                    s.install()
                net = self._install_net_schemes(cluster)
                try:
                    self._round(rnd, rng, svc, oracle, cluster,
                                live_ids, doc_id, report)
                    doc_id += self.docs_per_round
                finally:
                    for i, s in enumerate(schemes):
                        s.remove()
                        # names[i] keys the hit counts: two schemes of
                        # one class in a round must not overwrite
                        report["scheme_hits"][
                            f"r{rnd}:{names[i]}"] = s.hits
                    for s in net:
                        s.remove()
                # barrier: seal the round's writes and verify
                svc.refresh()
                oracle.refresh()
                self._verify_round(svc, oracle, rng, live_ids, report)
            # ---- frozen-corpus phase: overload under transport faults -
            if self.with_overload:
                self._verify_overload(svc, oracle, rng, cluster, report)
            # ---- frozen-corpus phase: ledger leak-freedom -------------
            self._verify_ledger_and_recovery(svc, oracle, warm_body,
                                             report)
            if cluster is not None:
                self._verify_cluster(cluster, report)
            report["planes_seen"] = sorted(report["planes_seen"])
            return report
        finally:
            dis.clear_search_disruptions()
            if cluster is not None:
                self._stop_cluster(cluster)
            svc.close()
            oracle.close()

    # -- round execution -------------------------------------------------

    def _round(self, rnd: int, rng, svc, oracle, cluster, live_ids,
               doc_base: int, report: dict) -> None:
        errors: List[str] = []
        # pre-generate all inputs on the seeded rng (threads must not
        # pull from a shared rng in nondeterministic order)
        docs = [(doc_base + i, self._doc(rng, doc_base + i))
                for i in range(self.docs_per_round)]
        delete_pick = (live_ids[int(rng.randint(len(live_ids)))]
                       if live_ids else None)
        queries = [[self._query(rng)
                    for _ in range(self.searches_per_round)]
                   for _ in range(self.search_threads)]

        def writer():
            try:
                for d, doc in docs:
                    svc.index_doc(str(d), doc)
                    oracle.index_doc(str(d), doc)
                    live_ids.append(str(d))
                    report["acked_writes"] += 1
                    if cluster is not None:
                        self._cluster_write(cluster, str(d), doc, report)
                if delete_pick is not None:
                    svc.delete_doc(delete_pick)
                    oracle.delete_doc(delete_pick)
                    live_ids.remove(delete_pick)
                    report["acked_deletes"] += 1
            except Exception as e:  # noqa: BLE001 — a lost ack IS the bug
                errors.append(f"writer: {type(e).__name__}: {e}")

        # per-thread counters, summed after join: a shared
        # read-modify-write from concurrent searchers can lose updates
        searched = [0] * self.search_threads

        def searcher(tid: int):
            for body in queries[tid]:
                try:
                    r = svc.search(dict(body))
                    if r["_shards"]["failed"]:
                        errors.append(
                            f"searcher{tid}: shard failures {r['_shards']}")
                    searched[tid] += 1
                except Exception as e:  # noqa: BLE001 — zero-5xx invariant
                    errors.append(
                        f"searcher{tid}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=writer, name="chaos-writer")]
        threads += [threading.Thread(target=searcher, args=(t,),
                                     name=f"chaos-search{t}")
                    for t in range(self.search_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        report["searches_under_fault"] += sum(searched)
        if errors:
            report["search_errors"] = errors
            raise ChaosSoakViolation(
                f"round {rnd} broke the zero-5xx/no-ack-loss invariant: "
                f"{errors[:4]}")

    def _verify_round(self, svc, oracle, rng, live_ids,
                      report: dict) -> None:
        # no acked-write loss: every acked write (minus acked deletes)
        # is visible on both indices
        body = {"query": {"match_all": {}}, "size": 0}
        got = svc.search(dict(body))["hits"]["total"]
        want = oracle.search(dict(body))["hits"]["total"]
        if got != len(live_ids) or want != len(live_ids):
            raise ChaosSoakViolation(
                f"acked-write loss: disrupted={got} oracle={want} "
                f"acked_live={len(live_ids)}")
        # byte-identical hits vs the oracle on a seeded query set
        self._assert_parity(
            svc, oracle, [self._query(rng) for _ in range(4)], report)

    # -- frozen-corpus overload phase (ISSUE 12, docs/OVERLOAD.md) ------

    def _verify_overload(self, svc, oracle, rng, cluster,
                         report: dict) -> None:
        """Overload + transport faults over the frozen corpus: pinned
        synthetic occupancy at queue capacity plus one blocked slot
        forces every arrival that cannot take the free slot into a
        clean 429 while admitted queries keep serving. Invariants:

        - zero 5xx: every offered query ends in a complete answer or
          an es_rejected_execution_exception carrying retry_after_s;
        - admitted-query hits (ids AND scores) stay byte-identical to
          the undisrupted oracle — brownout may shed features and
          report gte totals, never wrong hits;
        - no silent drops: rejected == offered − admitted, client-side
          AND in the controller's exact counters.
        """
        from elasticsearch_tpu.common.errors import (
            EsRejectedExecutionException,
        )

        queries = [self._query(rng) for _ in range(
            self.searches_per_round * 2)]
        # oracle answers pre-computed serially: the corpus is frozen, so
        # admitted hits under pressure must match these — ids exactly
        # always; scores exactly except under forced pruning, whose
        # different accumulation order shifts float32 results by an ulp
        # (ids and ranking stay exact; docs/PRUNING.md)
        want = {i: self._hits_key(oracle.search(dict(body)))
                for i, body in enumerate(queries)}

        def hits_match(resp, expect) -> bool:
            got = self._hits_key(resp)
            if [h[0] for h in got] != [h[0] for h in expect]:
                return False
            if resp.get("_pruned") or resp.get("_degraded"):
                return bool(np.allclose([h[1] for h in got],
                                        [h[1] for h in expect],
                                        rtol=2e-5, atol=1e-6))
            return got == expect
        base = svc.admission.stats_dict()
        schemes = [dis.QueuePressureScheme(
            occupancy=self.overload_queue_size, block_slots=1,
            drain_delay_s=0.001, indices=[self.index]).install()]
        net = self._install_net_schemes(cluster)
        counts = {"offered": 0, "admitted": 0, "rejected": 0}
        errors: List[str] = []
        lock = threading.Lock()

        def hammer(tid: int):
            for i, body in enumerate(queries):
                with lock:
                    counts["offered"] += 1
                try:
                    r = svc.search(dict(body))
                    if r["_shards"]["failed"]:
                        errors.append(
                            f"overload{tid}: failed shards {r['_shards']}")
                    elif not hits_match(r, want[i]):
                        errors.append(
                            f"overload{tid}: admitted hits diverged for "
                            f"{body!r}: {self._hits_key(r)} != {want[i]}")
                    with lock:
                        counts["admitted"] += 1
                except EsRejectedExecutionException as e:
                    if getattr(e, "retry_after_s", None) is None:
                        errors.append(
                            f"overload{tid}: 429 without retry_after_s")
                    with lock:
                        counts["rejected"] += 1
                except Exception as e:  # noqa: BLE001 — zero-5xx
                    errors.append(
                        f"overload{tid}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=hammer, args=(t,),
                                    name=f"chaos-overload{t}")
                   for t in range(max(self.search_threads, 2) + 1)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            for s in schemes:
                report["scheme_hits"][f"overload:{type(s).__name__}"] = \
                    s.hits
                s.remove()
            for s in net:
                s.remove()
        if errors:
            raise ChaosSoakViolation(
                f"overload phase broke an invariant: {errors[:4]}")
        if counts["rejected"] != counts["offered"] - counts["admitted"]:
            raise ChaosSoakViolation(
                f"silent drops under overload: {counts}")
        if counts["rejected"] == 0:
            raise ChaosSoakViolation(
                f"overload phase never rejected — the pinned occupancy "
                f"did not bite: {counts}")
        after = svc.admission.stats_dict()
        delta_adm = after["admitted_total"] - base["admitted_total"]
        delta_rej = after["rejected_total"] - base["rejected_total"]
        if (delta_adm != counts["admitted"]
                or delta_rej != counts["rejected"]):
            raise ChaosSoakViolation(
                f"admission counters drifted from the client's truth: "
                f"counters admitted={delta_adm} rejected={delta_rej} vs "
                f"{counts}")
        # pressure drained: the ladder steps back down and subsequent
        # queries are full-precision again (checked via _assert_parity
        # exact totals in the recovery phase below)
        svc.admission.refresh_level()
        report["overload"] = dict(
            counts, brownout_transitions=after["brownout_transitions"],
            retry_after_s=after["retry_after_s"])

    # -- frozen-corpus ledger + self-heal phase -------------------------

    def _verify_ledger_and_recovery(self, svc, oracle, warm_body,
                                    report: dict) -> None:
        from elasticsearch_tpu.common.memory import memory_accountant

        time.sleep(0.2)  # let the last quarantine cooldown lapse

        def heal(target):
            """Restage every scope a query can lazily stage: one query
            on the mesh rung (executor tables) and one pinned to the
            host rung (per-segment base + kernel tables) — the ledger
            snapshot below must only contain deterministically-healed
            scopes."""
            r = target.search(dict(warm_body))
            target._search_uncached(dict(warm_body), skip_mesh=True)
            return r

        # healing queries restage everything the fault rounds evicted,
        # and must land back on the fast plane
        healed = heal(svc)
        heal(oracle)
        if healed["_plane"] != "mesh_pallas":
            raise ChaosSoakViolation(
                f"index stranded off its fast plane after faults "
                f"cleared: _plane={healed['_plane']}")
        snap = {self.index: self._kind_bytes(self.index),
                self.oracle_index: self._kind_bytes(self.oracle_index)}
        # one more all-families fault burst over the FROZEN corpus
        burst = [
            dis.StagingFailScheme(kinds=["mesh_slot_tables"],
                                  transient=False, times=1,
                                  indices=[self.index]),
            dis.KernelLaunchFailScheme(rungs=("mesh_pallas", "batched"),
                                       times=1, indices=[self.index]),
            dis.EvictionStormScheme(period=2, indices=[self.index]),
            dis.SearchDelayScheme(0.001, indices=[self.index]),
        ]
        for s in burst:
            s.install()
        try:
            for _ in range(4):
                r = svc.search(dict(warm_body))
                if r["_shards"]["failed"]:
                    raise ChaosSoakViolation(
                        f"faults leaked into shard failures: "
                        f"{r['_shards']}")
        finally:
            for s in burst:
                s.remove()
                report["scheme_hits"][f"burst:{type(s).__name__}"] = s.hits
        time.sleep(0.2)  # quarantine cooldown (150ms default)
        healed = heal(svc)
        heal(oracle)
        if healed["_plane"] != "mesh_pallas":
            raise ChaosSoakViolation(
                f"post-burst healing query did not return to the fast "
                f"plane: _plane={healed['_plane']}")
        for name, before in snap.items():
            after = self._kind_bytes(name)
            if after != before:
                raise ChaosSoakViolation(
                    f"ledger leak on [{name}]: per-kind bytes did not "
                    f"return to the pre-burst snapshot\n before={before}"
                    f"\n after={after}")
        stats = memory_accountant().stats(self.index)
        amp = stats["restage_amplification"]
        report["restage_amplification"] = amp
        report["ledger_bytes"] = {k: v for k, v in
                                  snap[self.index].items() if v}
        if amp is not None and amp > self.amplification_bound:
            raise ChaosSoakViolation(
                f"restage amplification unbounded under the soak: "
                f"{amp} > {self.amplification_bound}")

    # -- transport-layer (PR 2) side: 2-node cluster ---------------------

    def _start_cluster(self):
        from elasticsearch_tpu.cluster.multinode import (
            ClusterClient,
            ClusterNode,
        )
        from elasticsearch_tpu.transport.local import TransportHub

        hub = TransportHub()
        nodes = {n: ClusterNode(n, hub, settings=_CLUSTER_SETTINGS)
                 for n in ("cn1", "cn2")}
        nodes["cn1"].bootstrap_cluster()
        nodes["cn2"].join("cn1")
        nodes["cn1"].create_index(
            self.index + "_tx",
            {"index": {"number_of_shards": 1, "number_of_replicas": 1}},
            {"properties": {"body": {"type": "text",
                                     "analyzer": "whitespace"}}})
        self._wait_cluster_started(nodes)
        return {"hub": hub, "nodes": nodes,
                "client": ClusterClient(nodes["cn1"]), "acked": []}

    def _wait_cluster_started(self, nodes, attempts: int = 80) -> None:
        from elasticsearch_tpu.cluster.state import ShardRoutingState

        master = nodes["cn1"]
        for _ in range(attempts):
            try:
                master.reroute()
            except Exception:  # noqa: BLE001 — disruption may bite
                pass
            routing = master.routing.get(self.index + "_tx", {})
            copies = [c for cs in routing.values() for c in cs]
            if copies and all(c.state == ShardRoutingState.STARTED
                              for c in copies):
                return
            time.sleep(0.05)
        raise ChaosSoakViolation("cluster copies never all STARTED")

    def _install_net_schemes(self, cluster) -> List:
        if cluster is None:
            return []
        return [
            dis.NetworkDrop(self.cluster_drop_p,
                            seed=self.seed).apply_to(cluster["hub"]),
            dis.NetworkDelay(0.002).apply_to(cluster["hub"]),
        ]

    def _cluster_write(self, cluster, doc_id: str, doc: dict,
                       report: dict) -> None:
        """A write is only counted once ACKED; transient transport
        errors retry (the reference client contract). An acked write
        that later vanishes is the invariant violation."""
        last = None
        for _ in range(6):
            try:
                cluster["client"].index(self.index + "_tx", doc_id,
                                        {"body": doc["body"]})
                cluster["acked"].append(doc_id)
                return
            except Exception as e:  # noqa: BLE001 — retry transients
                last = e
                time.sleep(0.05)
        raise ChaosSoakViolation(
            f"cluster write never acked for [{doc_id}]: {last}")

    def _verify_cluster(self, cluster, report: dict) -> None:
        client = cluster["client"]
        last = None
        for _ in range(40):
            try:
                client.refresh(self.index + "_tx")
                res = client.search(self.index + "_tx", {
                    "query": {"match_all": {}}, "size": 0})
                if res["_shards"]["failed"]:
                    raise ChaosSoakViolation(
                        f"cluster search failed shards with both copies "
                        f"alive: {res['_shards']}")
                if res["hits"]["total"] != len(cluster["acked"]):
                    raise ChaosSoakViolation(
                        f"acked-write loss on the cluster: "
                        f"{res['hits']['total']} != "
                        f"{len(cluster['acked'])} acked")
                report["cluster"] = {
                    "acked": len(cluster["acked"]),
                    "visible": res["hits"]["total"],
                }
                return
            except ChaosSoakViolation:
                raise
            except Exception as e:  # noqa: BLE001 — drops may still bite
                last = e
                time.sleep(0.1)
        raise ChaosSoakViolation(
            f"cluster never answered a clean search after healing: {last}")

    def _stop_cluster(self, cluster) -> None:
        cluster["hub"].clear_disruptions()
        for node in cluster["nodes"].values():
            close = getattr(node, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass

    # -- data-integrity corruption phase (ISSUE 16) ----------------------

    def run_corruption(self, data_root: str) -> dict:
        """Corruption chaos (docs/RESILIENCE.md "Data integrity"): inject
        real at-rest and in-flight store corruption, then assert the
        end-to-end integrity invariants:

        - **every injected corruption detected** — the integrity
          counters move for each injection; zero silent wrong results
          (a corrupt copy fails its shard per the partial-results
          contract, never serves);
        - **self-heal converges** — a corrupt replica re-recovers from
          the primary, a corrupt primary fails over to the STARTED
          replica and rebuilds, and post-heal hits are byte-identical
          to the pre-corruption answers;
        - **no acked-write loss** through every quarantine/heal cycle;
        - **ledger leak-free** — quarantine releases device staging
          through the accountant exactly (all scopes at zero after
          close).
        """
        return _run_witnessed(lambda: self._run_corruption(data_root))

    def _run_corruption(self, data_root: str) -> dict:
        from elasticsearch_tpu.common.integrity import integrity_service

        report: dict = {"seed": self.seed, "injected": 0}
        before = integrity_service().stats()
        report["local"] = self._corruption_local(report)
        report["cluster"] = self._corruption_cluster(data_root, report)
        after = integrity_service().stats()
        # device drift counts on its own axis (scrub_drift_total): the
        # staged copy drifted, not the store bytes
        detected = (after["corruption_detected_total"]
                    - before["corruption_detected_total"]
                    + after["scrub_drift_total"]
                    - before["scrub_drift_total"])
        report["detected"] = detected
        if detected < report["injected"]:
            raise ChaosSoakViolation(
                f"silent corruption: injected {report['injected']} faults "
                f"but only {detected} detections were counted "
                f"(by_site={after['corruption_detected_by_site']})")
        return report

    def _corruption_local(self, report: dict) -> dict:
        """In-process detection matrix: at-rest checksum corruption is
        caught by the scrubber and degrades queries per the PR-4 partial
        contract; device-staging drift is caught by the scrub digest
        compare, restaged, and never serves."""
        from elasticsearch_tpu.common.memory import memory_accountant

        out: dict = {}
        svc = self._mk_index(self.index + "_int")
        oracle = self._mk_index(self.index + "_int_oracle")
        try:
            rng = np.random.RandomState(self.seed + 3)
            for d in range(self.seed_docs):
                doc = self._doc(rng, d)
                svc.index_doc(str(d), doc)
                oracle.index_doc(str(d), doc)
            svc.refresh()
            oracle.refresh()
            svc.flush()  # sealed, checksummed segments on disk
            oracle.flush()
            probe = {"query": {"match": {"body": self.vocab[0]}},
                     "size": 10}
            want = self._hits_key(oracle.search(dict(probe)))
            if self._hits_key(svc.search(dict(probe))) != want:
                raise ChaosSoakViolation("corpora diverged before faults")

            # --- at-rest: bit-flip a committed array, scrub detects ----
            scheme = dis.StoreCorruptionScheme("bitflip", seed=self.seed)
            scheme.corrupt_store(svc.shards[0].engine.store)
            report["injected"] += 1
            scrub = svc.scrub_now()
            if scrub["checksum_failures"] < 1:
                raise ChaosSoakViolation(
                    f"scrub missed the injected at-rest corruption: "
                    f"{scrub} (corrupted: {scheme.corrupted})")
            if not svc.shards[0].store_corrupted \
                    or not svc.shards[0].engine.store.is_corrupted():
                raise ChaosSoakViolation(
                    "scrub detection did not quarantine the copy")
            # quarantine released the copy's device staging exactly
            for seg in svc.shards[0].engine.searchable_segments():
                if seg._device:
                    raise ChaosSoakViolation(
                        f"quarantined shard still holds device staging "
                        f"for segment [{seg.name}]")
            # partial contract: failures[] + degraded 200, never a raise
            r = svc.search(dict(probe))
            if not r["_shards"]["failed"]:
                raise ChaosSoakViolation(
                    "quarantined shard served instead of failing "
                    "(zero-silent-wrong-results violated)")
            out["at_rest"] = {"scrub": scrub,
                              "failed_shards": r["_shards"]["failed"]}

            # --- device drift: tamper a staged table, scrub restages ---
            # stage the per-segment host-path tables (the mesh plane
            # keeps its own executor tables; the drift scan below reads
            # Segment._device)
            oracle._search_uncached(dict(probe), skip_mesh=True)
            drifted = None
            for shard in oracle.shards.values():
                for seg in shard.engine.searchable_segments():
                    dev = getattr(seg, "_device", None) or {}
                    if dev.get("norms") is not None:
                        import jax.numpy as jnp

                        host = np.asarray(dev["norms"]).copy()
                        host.flat[0] = host.flat[0] + 1.0
                        dev["norms"] = jnp.asarray(host)
                        drifted = seg.name
                        break
                if drifted:
                    break
            if drifted is None:
                raise ChaosSoakViolation(
                    "no staged norms table found to drift")
            report["injected"] += 1
            scrub2 = oracle.scrub_now()
            if scrub2["drift"] < 1:
                raise ChaosSoakViolation(
                    f"scrub missed the injected device drift on "
                    f"[{drifted}]: {scrub2}")
            if self._hits_key(oracle.search(dict(probe))) != want:
                raise ChaosSoakViolation(
                    "drifted staging served wrong results after restage")
            out["drift"] = {"segment": drifted, "scrub": scrub2}
            return out
        finally:
            svc.close()
            oracle.close()
            # ledger leak-free: the quarantine release path + close must
            # return every scope to zero — no stranded HBM bytes
            for name in (self.index + "_int", self.index + "_int_oracle"):
                leaked = {k: v for k, v in memory_accountant()
                          .staged_bytes_by_kind(name).items() if v}
                if leaked:
                    raise ChaosSoakViolation(
                        f"ledger leak through the corruption phase on "
                        f"[{name}]: {leaked}")

    def _corruption_cluster(self, data_root: str, report: dict) -> dict:
        """Replicated self-heal: corrupt replica → re-recovers from the
        primary; corrupt primary → fails over to the STARTED replica and
        rebuilds; in-flight recovery corruption → digest mismatch
        detected, session retried once, heals. Green + byte-identical
        hits + zero acked-write loss after every cycle."""
        import os
        import shutil

        from elasticsearch_tpu.common.integrity import integrity_service
        from elasticsearch_tpu.cluster.multinode import (
            ClusterClient,
            ClusterNode,
        )
        from elasticsearch_tpu.index.store import Store
        from elasticsearch_tpu.transport.local import TransportHub

        idx = self.index + "_int_tx"
        hub = TransportHub()
        mk = lambda n: ClusterNode(  # noqa: E731
            n, hub, settings=_CLUSTER_SETTINGS,
            data_path=os.path.join(data_root, "int_cluster", n))
        names = ["int1", "int2"]
        nodes = {n: mk(n) for n in names}
        out: dict = {"scenarios": []}
        try:
            nodes["int1"].bootstrap_cluster()
            nodes["int2"].join("int1")
            nodes["int1"].create_index(idx, {
                "index": {"number_of_shards": 1,
                          "number_of_replicas": 1}},
                {"properties": {"body": {"type": "text",
                                         "analyzer": "whitespace"},
                                "n": {"type": "integer"}}})
            self._wait_copies_started(nodes, idx)
            rng = np.random.RandomState(self.seed + 4)
            acked: List[str] = []
            client = ClusterClient(nodes["int1"])
            for d in range(self.seed_docs // 2):
                doc = self._doc(rng, d)
                client.index(idx, str(d), {"body": doc["body"],
                                           "n": int(d)})
                acked.append(str(d))
            client.refresh(idx)
            ordered = {"query": {"match_all": {}},
                       "sort": [{"n": "asc"}], "size": len(acked)}
            want = self._cluster_hits(client, idx, ordered)

            def roll_with_corruption(victim: str, wipe: bool,
                                     in_flight: bool) -> dict:
                """Close ``victim``, corrupt (or wipe) its store, restart
                it, and let recovery self-heal. Returns scenario stats."""
                store_dir = nodes[victim].shards[(idx, 0)] \
                    .engine.store.directory
                base = integrity_service().stats()
                nodes[victim].close(graceful=True)
                scheme = None
                if wipe:
                    shutil.rmtree(os.path.dirname(store_dir),
                                  ignore_errors=True)
                else:
                    dis.StoreCorruptionScheme(
                        "bitflip", seed=self.seed).corrupt_store(
                        Store(store_dir))
                    report["injected"] += 1
                if in_flight:
                    survivor_node = next(nodes[n] for n in names
                                         if n != victim)
                    scheme = dis.StoreCorruptionScheme(
                        "bitflip", seed=self.seed,
                        source_node=survivor_node).apply_to(hub)
                    report["injected"] += 1
                try:
                    nodes[victim] = mk(victim)
                    nodes[victim].join(next(n for n in names
                                            if n != victim))
                    self._wait_copies_started(nodes, idx)
                finally:
                    if scheme is not None:
                        scheme.remove()
                        if not scheme.hits:
                            raise ChaosSoakViolation(
                                "in-flight corruption scheme never fired")
                # the healed copy left quarantine: markers gone
                markers = Store(store_dir).corruption_markers()
                if markers:
                    raise ChaosSoakViolation(
                        f"healed copy still carries markers: {markers}")
                after = integrity_service().stats()
                return {
                    "victim": victim,
                    "detected": after["corruption_detected_total"]
                        - base["corruption_detected_total"],
                    "by_site": {
                        s: after["corruption_detected_by_site"][s]
                        - base["corruption_detected_by_site"][s]
                        for s in after["corruption_detected_by_site"]},
                    "cleared": after["markers_cleared_total"]
                        - base["markers_cleared_total"],
                }

            def verify_green(tag: str) -> None:
                client = ClusterClient(nodes["int1"])
                client.refresh(idx)
                res = client.search(idx, {"query": {"match_all": {}},
                                          "size": 0})
                if res["_shards"]["failed"]:
                    raise ChaosSoakViolation(
                        f"[{tag}] shard failures after heal: "
                        f"{res['_shards']}")
                if res["hits"]["total"] != len(acked):
                    raise ChaosSoakViolation(
                        f"[{tag}] acked-write loss: "
                        f"{res['hits']['total']} != {len(acked)}")
                got = self._cluster_hits(client, idx, ordered)
                if got != want:
                    raise ChaosSoakViolation(
                        f"[{tag}] post-heal hits diverged:\n got: {got}"
                        f"\nwant: {want}")

            # scenario 1: corrupt REPLICA re-recovers from the primary
            primary = self._primary_node(nodes, idx)
            replica = next(n for n in names if n != primary)
            s1 = roll_with_corruption(replica, wipe=False,
                                      in_flight=False)
            if s1["by_site"].get("load", 0) < 1:
                raise ChaosSoakViolation(
                    f"corrupt replica not detected at load: {s1}")
            if s1["cleared"] < 1:
                raise ChaosSoakViolation(
                    f"replica heal cleared no markers: {s1}")
            verify_green("corrupt-replica")
            s1["scenario"] = "corrupt_replica"
            out["scenarios"].append(s1)

            # scenario 2: corrupt PRIMARY fails over to the STARTED
            # replica, then rebuilds from the promoted copy
            primary = self._primary_node(nodes, idx)
            s2 = roll_with_corruption(primary, wipe=False,
                                      in_flight=False)
            if s2["by_site"].get("load", 0) < 1:
                raise ChaosSoakViolation(
                    f"corrupt primary not detected at load: {s2}")
            new_primary = self._primary_node(nodes, idx)
            if new_primary == primary:
                raise ChaosSoakViolation(
                    "corrupt primary did not fail over to the replica")
            verify_green("corrupt-primary")
            s2["scenario"] = "corrupt_primary"
            out["scenarios"].append(s2)

            # scenario 3: in-flight recovery corruption — the shipped
            # bytes stop matching the manifest digests, the target
            # detects, the session retries once and heals
            primary = self._primary_node(nodes, idx)
            replica = next(n for n in names if n != primary)
            s3 = roll_with_corruption(replica, wipe=True, in_flight=True)
            if s3["by_site"].get("recovery", 0) < 1:
                raise ChaosSoakViolation(
                    f"in-flight corruption not detected at the "
                    f"recovery install: {s3}")
            verify_green("in-flight-recovery")
            s3["scenario"] = "recovery_in_flight"
            out["scenarios"].append(s3)
            return out
        finally:
            hub.clear_disruptions()
            for node in nodes.values():
                try:
                    node.close(graceful=False)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass

    @staticmethod
    def _cluster_hits(client, idx: str, body: dict) -> list:
        resp = client.search(idx, dict(body))
        return [(h["_id"], h["_score"], tuple(h.get("sort") or ()))
                for h in resp["hits"]["hits"]]

    def _primary_node(self, nodes, idx: str) -> str:
        master = next(n for n in nodes.values() if n.is_master)
        copies = master.routing[idx][0]
        return next(c.node_id for c in copies if c.primary)

    def _wait_copies_started(self, nodes, idx: str,
                             attempts: int = 100) -> None:
        from elasticsearch_tpu.cluster.state import ShardRoutingState

        for _ in range(attempts):
            master = next((n for n in nodes.values() if n.is_master),
                          None)
            if master is not None:
                try:
                    master.reroute()
                except Exception:  # noqa: BLE001 — mid-heal churn
                    pass
                routing = master.routing.get(idx, {})
                copies = [c for cs in routing.values() for c in cs]
                if copies and all(c.state == ShardRoutingState.STARTED
                                  for c in copies):
                    return
            time.sleep(0.05)
        raise ChaosSoakViolation(
            f"copies of [{idx}] never all reached STARTED")


class RollingRestartSoak:
    """Zero-downtime rollout soak (ISSUE 14, docs/RESILIENCE.md
    "Rollout & drain"): restart must be a measured non-event. Four
    phases, each seeded and deterministic:

    1. **drain** — a Node under concurrent slow searches drains:
       in-flight searches finish inside the deadline, new arrivals get
       the clean 503 + Retry-After (never a 5xx), queued entries are
       shed with exact counters, and the shutdown stamps synced-flush
       markers.
    2. **warm restart** — the drained node restarts over the same data
       path: `_cat/recovery` shows ZERO translog ops replayed (the
       synced-flush contract) and search results are byte-identical
       (ids AND scores) on the restored planes.
    3. **rolling cluster restart** — every node of a replicated
       multinode cluster rolls (graceful leave → close → restart →
       rejoin → recover) under concurrent zipfian search + bulk
       ingest: no acked-write loss, zero non-429/503 errors, the
       departing node's primaries promote on the leave publish (not
       the FD timeout), and post-roll hits are byte-identical to an
       undisrupted oracle.
    4. **compile-warm restart** — with the persistent compilation
       cache + variant registry active, a simulated process restart
       (compiled-program caches dropped, registry reloaded from disk)
       warms the recorded lattice off the query path: the post-restart
       query set pays ZERO query-path first compiles, and the
       device-memory ledger returns byte-exactly to its pre-restart
       per-kind snapshot.
    """

    def __init__(self, data_root: str, seed: int = 0, nodes: int = 3,
                 shards: int = 2, seed_docs: int = 24,
                 docs_per_roll: int = 8, searches_per_roll: int = 6,
                 drain_searches: int = 4, index: str = "roll"):
        self.data_root = data_root
        self.seed = int(seed)
        self.n_nodes = int(nodes)
        self.shards = int(shards)
        self.seed_docs = int(seed_docs)
        self.docs_per_roll = int(docs_per_roll)
        self.searches_per_roll = int(searches_per_roll)
        self.drain_searches = int(drain_searches)
        self.index = index
        self.vocab = [f"w{i}" for i in range(12)]

    # -- shared helpers --------------------------------------------------

    def _zipf_term(self, rng: np.random.RandomState) -> int:
        return min(int(rng.zipf(1.4)) - 1, len(self.vocab) - 1)

    def _doc(self, rng: np.random.RandomState, d: int) -> dict:
        toks = [self.vocab[self._zipf_term(rng)]
                for _ in range(3 + int(rng.randint(4)))]
        return {"body": " ".join(toks), "n": int(d)}

    @staticmethod
    def _hits_key(resp) -> list:
        return [(h["_id"], h["_score"], tuple(h.get("sort") or ()))
                for h in resp["hits"]["hits"]]

    # -- phase 1+2: drain + warm restart of a single node ----------------

    def run_drain_and_warm_restart(self) -> dict:
        import os

        from elasticsearch_tpu.common.errors import NodeDrainingException
        from elasticsearch_tpu.cluster.multinode import (
            clear_recovery_progress,
            recovery_progress_rows,
        )
        from elasticsearch_tpu.node import Node

        clear_recovery_progress()
        report: dict = {"in_flight_ok": 0, "drain_rejects": 0,
                        "errors": []}
        rng = np.random.RandomState(self.seed)
        path = os.path.join(self.data_root, "drain_node")
        node = Node(Settings({
            "search.drain.deadline": "10s",
        }), data_path=path)
        node.create_index(self.index, {"settings": {
            "index.number_of_shards": self.shards,
            "index.refresh_interval": -1}})
        for d in range(self.seed_docs):
            node.index_doc(self.index, str(d), self._doc(rng, d))
        node.indices[self.index].refresh()
        probe = {"query": {"match": {"body": self.vocab[0]}}, "size": 10}
        want = self._hits_key(node.search(self.index, dict(probe)))

        # concurrent slow searches in flight while the drain begins
        slow = dis.SearchDelayScheme(0.05, indices=[self.index]).install()
        started = threading.Barrier(self.drain_searches + 1)

        def searcher():
            try:
                started.wait(timeout=5)
                r = node.search(self.index, dict(probe))
                if r["_shards"]["failed"]:
                    report["errors"].append(f"failed shards {r['_shards']}")
                else:
                    report["in_flight_ok"] += 1
            except NodeDrainingException:
                # admitted-before-drain is not guaranteed for every
                # thread — a clean 503 is the other legal outcome
                report["drain_rejects"] += 1
            except Exception as e:  # noqa: BLE001 — anything else is the bug
                report["errors"].append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=searcher)
                   for _ in range(self.drain_searches)]
        for t in threads:
            t.start()
        started.wait(timeout=5)
        time.sleep(0.01)  # let the searchers acquire their slots
        drain = node.drain()
        for t in threads:
            t.join()
        slow.remove()
        report["drain"] = drain
        if not drain["drained"] or drain["in_flight_remaining"]:
            raise ChaosSoakViolation(
                f"drain did not quiesce in-flight work: {drain}")
        if report["errors"]:
            raise ChaosSoakViolation(
                f"drain leaked non-503 errors: {report['errors'][:4]}")
        # draining node refuses new work with the clean 503 + Retry-After
        try:
            node.search(self.index, dict(probe))
            raise ChaosSoakViolation("draining node admitted a search")
        except NodeDrainingException as e:
            if getattr(e, "retry_after_s", None) is None:
                raise ChaosSoakViolation("drain 503 without Retry-After")
            report["drain_rejects"] += 1
        adm = node.indices[self.index].admission.stats_dict()
        if not adm["draining"] or adm["drain_rejected_total"] < 1:
            raise ChaosSoakViolation(f"drain state not exported: {adm}")
        node.close()

        # warm restart over the same data path: ops-free + byte-identical
        node2 = Node(Settings({"index.refresh_interval": "-1"}),
                     data_path=path)
        try:
            rows = [r for r in recovery_progress_rows()
                    if r["index"] == self.index and r["type"] == "store"]
            if not rows:
                raise ChaosSoakViolation("no store-recovery rows recorded")
            replayed = sum(r["ops_recovered"] for r in rows)
            if replayed:
                raise ChaosSoakViolation(
                    f"warm restart replayed {replayed} translog ops "
                    f"despite the synced flush (rows: {rows})")
            got = self._hits_key(node2.search(self.index, dict(probe)))
            if got != want:
                raise ChaosSoakViolation(
                    f"restart changed results: {got} != {want}")
            for sid, shard in node2.indices[self.index].shards.items():
                if shard.engine.last_sync_id is None:
                    raise ChaosSoakViolation(
                        f"shard {sid} lost its synced-flush marker")
            report["ops_replayed"] = replayed
            report["restart_hits_identical"] = True
        finally:
            node2.close()
        return report

    # -- phase 3: rolling restart of a replicated cluster ----------------

    def run_rolling_cluster(self) -> dict:
        import os

        from elasticsearch_tpu.cluster.multinode import (
            ClusterClient,
            ClusterNode,
        )
        from elasticsearch_tpu.index.index_service import IndexService
        from elasticsearch_tpu.transport.local import TransportHub

        rng = np.random.RandomState(self.seed + 1)
        hub = TransportHub()
        names = [f"roll{i}" for i in range(self.n_nodes)]
        mk = lambda n: ClusterNode(  # noqa: E731
            n, hub, settings=_CLUSTER_SETTINGS,
            data_path=os.path.join(self.data_root, "cluster", n))
        nodes = {n: mk(n) for n in names}
        nodes[names[0]].bootstrap_cluster()
        for n in names[1:]:
            nodes[n].join(names[0])
        idx = self.index + "_c"
        nodes[names[0]].create_index(idx, {
            "index": {"number_of_shards": self.shards,
                      "number_of_replicas": 1}},
            {"properties": {"body": {"type": "text",
                                     "analyzer": "whitespace"},
                            "n": {"type": "integer"}}})
        self._wait_all_started(nodes, idx)
        # undisrupted oracle: same shard count => same routing + stats
        oracle = IndexService(idx + "_oracle", Settings({
            "index.number_of_shards": self.shards,
            "index.refresh_interval": -1}),
            mapping={"properties": {
                "body": {"type": "text", "analyzer": "whitespace"},
                "n": {"type": "integer"}}})
        report: dict = {"acked": 0, "rolls": [], "errors": [],
                        "searches_during_roll": 0,
                        "write_retries": 0}
        acked: List[str] = []

        def write(client, doc_id: str, doc: dict) -> None:
            last = None
            for attempt in range(8):
                try:
                    client.index(idx, doc_id, doc)
                    acked.append(doc_id)
                    oracle.index_doc(doc_id, doc)
                    report["acked"] += 1
                    if attempt:
                        report["write_retries"] += attempt
                    return
                except Exception as e:  # noqa: BLE001 — roll in progress
                    last = e
                    time.sleep(0.05)
            raise ChaosSoakViolation(
                f"write [{doc_id}] never acked through the roll: {last}")

        try:
            doc_id = 0
            client0 = ClusterClient(nodes[names[0]])
            for _ in range(self.seed_docs):
                write(client0, str(doc_id), self._doc(rng, doc_id))
                doc_id += 1
            for victim in list(names):
                survivor = next(n for n in names if n != victim)
                client = ClusterClient(nodes[survivor])
                stop = threading.Event()
                errors: List[str] = []
                searched = [0]

                def load(client=client):
                    q_rng = np.random.RandomState(self.seed + 7)
                    while not stop.is_set():
                        body = {"query": {"match": {
                            "body": self.vocab[self._zipf_term(q_rng)]}},
                            "size": 5}
                        try:
                            r = client.search(idx, body)
                            # degraded-but-clean is legal mid-roll; a
                            # RAISE that is not a 429/503 is the bug
                            _ = r["hits"]["total"]
                            searched[0] += 1
                        except Exception as e:  # noqa: BLE001
                            status = getattr(e, "status_code", 500)
                            if status not in (429, 503):
                                errors.append(
                                    f"{type(e).__name__}: {e}")
                        time.sleep(0.005)

                loader = threading.Thread(target=load)
                loader.start()
                t0 = time.monotonic()
                try:
                    # a few writes through the survivor DURING the roll
                    nodes[victim].close(graceful=True)
                    master = next(n for n in names
                                  if n != victim
                                  and nodes[n].master_id is not None)
                    if victim in nodes[master].known_nodes:
                        raise ChaosSoakViolation(
                            f"[{victim}] still in known_nodes after a "
                            f"graceful leave")
                    self._assert_primaries_available(
                        nodes, idx, exclude=victim)
                    for _ in range(self.docs_per_roll):
                        write(client, str(doc_id),
                              self._doc(rng, doc_id))
                        doc_id += 1
                    # restart over the same data path and rejoin
                    nodes[victim] = mk(victim)
                    nodes[victim].join(survivor)
                    self._wait_all_started(nodes, idx)
                finally:
                    stop.set()
                    loader.join()
                if errors:
                    raise ChaosSoakViolation(
                        f"roll of [{victim}] leaked non-429/503 errors: "
                        f"{errors[:4]}")
                report["searches_during_roll"] += searched[0]
                report["rolls"].append({
                    "node": victim, "took_ms":
                        int((time.monotonic() - t0) * 1000)})
            # barrier: all writes acked — verify totals + byte-identity
            client = ClusterClient(nodes[names[0]])
            client.refresh(idx)
            oracle.refresh()
            res = client.search(idx, {"query": {"match_all": {}},
                                      "size": 0})
            if res["hits"]["total"] != len(acked):
                raise ChaosSoakViolation(
                    f"acked-write loss through the roll: "
                    f"{res['hits']['total']} != {len(acked)}")
            # deterministic ordered query: byte-identical sort values,
            # ids, and order vs the oracle (scores are None both sides)
            body = {"query": {"match_all": {}},
                    "sort": [{"n": "asc"}], "size": 20}
            got = self._hits_key(client.search(idx, dict(body)))
            want = self._hits_key(oracle.search(dict(body)))
            if got != want:
                raise ChaosSoakViolation(
                    f"post-roll hits diverged from the oracle:\n got: "
                    f"{got}\nwant: {want}")
            report["hits_identical"] = True
            return report
        finally:
            for n in nodes.values():
                try:
                    n.close(graceful=False)
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            oracle.close()

    def _wait_all_started(self, nodes, idx, attempts: int = 100) -> None:
        from elasticsearch_tpu.cluster.state import ShardRoutingState

        for _ in range(attempts):
            master = next((n for n in nodes.values() if n.is_master), None)
            if master is not None:
                try:
                    master.reroute()
                except Exception:  # noqa: BLE001 — mid-roll churn
                    pass
                routing = master.routing.get(idx, {})
                copies = [c for cs in routing.values() for c in cs]
                if copies and all(c.state == ShardRoutingState.STARTED
                                  for c in copies):
                    return
            time.sleep(0.05)
        raise ChaosSoakViolation(
            f"cluster copies of [{idx}] never all reached STARTED")

    def _assert_primaries_available(self, nodes, idx, exclude) -> None:
        master = next((n for name, n in nodes.items()
                       if name != exclude and n.is_master), None)
        if master is None:
            raise ChaosSoakViolation("no master after a graceful leave")
        for sid, copies in master.routing.get(idx, {}).items():
            primary = next((c for c in copies if c.primary), None)
            if primary is None or primary.node_id == exclude:
                raise ChaosSoakViolation(
                    f"shard [{sid}] has no promoted primary after the "
                    f"leave (copies: {copies})")

    # -- phase 4: compile-cache warm restart -----------------------------

    def run_compile_warm_restart(self) -> dict:
        import os

        from elasticsearch_tpu.common import compile_cache as cc
        from elasticsearch_tpu.common.memory import memory_accountant
        from elasticsearch_tpu.index.index_service import IndexService
        from elasticsearch_tpu.parallel.plan_exec import (
            clear_compiled_programs,
        )

        rng = np.random.RandomState(self.seed + 2)
        idx = self.index + "_warm"
        data_path = os.path.join(self.data_root, "warm_index")
        prev_registry = cc.variant_registry()
        cc.configure_compile_cache(
            os.path.join(self.data_root, "jax_cache"))
        registry_path = os.path.join(self.data_root,
                                     "compile_variants.json")
        cc.set_variant_registry(cc.VariantRegistry(registry_path))
        settings = Settings({
            "index.number_of_shards": self.shards,
            "index.search.mesh": True,
            "index.search.mesh.plane": "pallas",
            "index.refresh_interval": -1,
        })
        mapping = {"properties": {
            "body": {"type": "text", "analyzer": "whitespace"},
            "n": {"type": "integer"}}}

        def mk():
            return IndexService(idx, settings, mapping=mapping,
                                data_path=data_path)

        queries = [
            {"query": {"match": {"body": self.vocab[0]}}, "size": 10},
            {"query": {"match": {"body": f"{self.vocab[1]} "
                                         f"{self.vocab[2]}"}}, "size": 5},
        ]
        svc = mk()
        for d in range(self.seed_docs):
            svc.index_doc(str(d), self._doc(rng, d))
        svc.refresh()
        svc.flush()
        want = [self._hits_key(svc.search(dict(q))) for q in queries]
        plane = svc.search(dict(queries[0]))["_plane"]
        if plane not in ("mesh_pallas", "mesh"):
            raise ChaosSoakViolation(
                f"compile-warm phase needs the mesh plane, got {plane}")
        if not cc.variant_registry().warm_entries(idx):
            raise ChaosSoakViolation(
                "mesh-served queries recorded no warmable variants")
        ledger_before = memory_accountant().staged_bytes_by_kind(idx)
        svc.close()

        # simulated process restart: compiled programs gone, registry
        # reloaded from disk (preexisting => hits), same data path
        clear_compiled_programs()
        cc.set_variant_registry(cc.VariantRegistry(registry_path))
        svc2 = mk()
        try:
            stats_pre = cc.compile_stats().stats()
            warmed = svc2.warm_compile_variants()
            if warmed < 1:
                raise ChaosSoakViolation("warm replay covered nothing")
            stats0 = cc.compile_stats().stats()
            got = [self._hits_key(svc2.search(dict(q))) for q in queries]
            stats1 = cc.compile_stats().stats()
            delta = (stats1["query_path_first_compile_total"]
                     - stats0["query_path_first_compile_total"])
            if delta:
                raise ChaosSoakViolation(
                    f"warmed restart paid {delta} query-path first "
                    f"compiles (events: "
                    f"{stats1['first_compile_events'][-4:]})")
            if got != want:
                raise ChaosSoakViolation(
                    f"warmed restart changed results: {got} != {want}")
            ledger_after = memory_accountant().staged_bytes_by_kind(idx)
            if ledger_after != ledger_before:
                raise ChaosSoakViolation(
                    f"ledger not restored after the warmed restart:\n "
                    f"before={ledger_before}\n after={ledger_after}")
            return {
                "warm_specs_replayed": warmed,
                "programs_warmed": stats1["programs_warmed_total"]
                    - stats_pre["programs_warmed_total"],
                "cache_hits": stats1["compile_cache_hit_total"],
                "query_path_first_compiles": delta,
                "hits_identical": True,
                "ledger_restored": True,
            }
        finally:
            svc2.close()
            # restore process-global compile-plane state: the soak's
            # data_root (and the jax cache dir inside it) may be a
            # temporary directory the caller deletes
            cc.configure_compile_cache(None)
            cc.set_variant_registry(prev_registry)

    # -- the whole soak --------------------------------------------------

    def run(self) -> dict:
        # same witness contract as ChaosSoak.run: the rolling restarts
        # exercise drain/promotion/recovery lock paths the steady-state
        # soak never takes, so they confirm docs/LOCK_ORDER.md too
        return _run_witnessed(lambda: {
            "seed": self.seed,
            "drain": self.run_drain_and_warm_restart(),
            "cluster": self.run_rolling_cluster(),
            "compile": self.run_compile_warm_restart(),
        })
