"""Test infrastructure: the YAML REST conformance runner and helpers
(the analog of the reference's test/framework module)."""
