"""YAML REST conformance runner.

Executes the reference's language-agnostic REST test suite
(rest-api-spec/src/main/resources/rest-api-spec/test/**) against this
engine's HTTP surface — the compatibility metric SURVEY §4.6.4 calls
"reusable nearly verbatim". Role model:
test/framework/src/main/java/org/elasticsearch/test/rest/yaml/
ESClientYamlSuiteTestCase.java and its section classes (DoSection,
MatchAssertion, LengthAssertion, SetSection, SkipSection).

Requests are constructed generically from the reference's API specs
(rest-api-spec/src/main/resources/rest-api-spec/api/*.json): the best
matching URL template is the longest whose {parts} are all provided;
remaining arguments become query params; `body` is JSON (or newline-
delimited JSON for bulk-style endpoints).

Supported step types: do (with catch), match (incl. /regex/ values and
$stash substitution), length, is_true, is_false, gt/gte/lt/lte, set.
Skip sections honor `version` ranges (this engine presents as 6.0.0)
and a feature allowlist.
"""

from __future__ import annotations

import json
import re
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

import yaml

# what we present to `skip: version:` ranges (the reference line we track)
ENGINE_VERSION = (6, 0, 0)
SUPPORTED_FEATURES = {"stash_in_path", "stash_in_key"}


class YamlTestSkipped(Exception):
    pass


class YamlTestFailure(AssertionError):
    pass


# ----------------------------------------------------------------------
# API specs
# ----------------------------------------------------------------------


class ApiSpecs:
    def __init__(self, api_dir: str):
        import os

        self.apis: Dict[str, dict] = {}
        for name in os.listdir(api_dir):
            if not name.endswith(".json") or name == "_common.json":
                continue
            with open(os.path.join(api_dir, name), encoding="utf-8") as f:
                spec = json.load(f)
            for api_name, api in spec.items():
                self.apis[api_name] = api

    def build_request(self, api_name: str, args: dict
                      ) -> Tuple[str, str, dict, Any]:
        """Returns (method, path, query_params, body)."""
        api = self.apis.get(api_name)
        if api is None:
            raise YamlTestFailure(f"unknown api [{api_name}]")
        args = dict(args)
        body = args.pop("body", None)
        url = api["url"]
        part_names = set((url.get("parts") or {}).keys())
        # choose the longest path whose {parts} are all provided
        best, best_parts = None, -1
        for path in url.get("paths", [url.get("path")]):
            parts = re.findall(r"{(\w+)}", path)
            if all(p in args and args[p] is not None for p in parts):
                if len(parts) > best_parts:
                    best, best_parts = path, len(parts)
        if best is None:
            raise YamlTestFailure(
                f"[{api_name}] no path matches args {sorted(args)}")
        path = best
        used = set()
        for p in re.findall(r"{(\w+)}", path):
            val = args[p]
            if isinstance(val, (list, tuple)):
                val = ",".join(str(v) for v in val)
            path = path.replace("{" + p + "}",
                                urllib.parse.quote(str(val), safe=""))
            used.add(p)
        params = {k: v for k, v in args.items()
                  if k not in used and k not in part_names and v is not None}
        methods = api.get("methods", ["GET"])
        if body is not None and "GET" in methods and len(methods) > 1:
            method = next(m for m in methods if m != "GET")
        elif body is not None and methods == ["GET"]:
            method = "GET"
        else:
            method = methods[0]
        # prefer PUT for doc-targeting index/create calls (id in path)
        if api_name in ("index", "create") and "{id}" in best:
            method = "PUT" if "PUT" in methods else method
        return method, path, params, body


# ----------------------------------------------------------------------
# Stash + response path lookups
# ----------------------------------------------------------------------


def stash_sub(value: Any, stash: dict) -> Any:
    if isinstance(value, str):
        if value.startswith("$"):
            key = value[1:]
            if key in stash:
                return stash[key]
        # ${...} inline form
        def repl(m):
            return str(stash.get(m.group(1), m.group(0)))

        return re.sub(r"\$\{(\w+)\}", repl, value)
    if isinstance(value, dict):
        return {stash_sub(k, stash): stash_sub(v, stash)
                for k, v in value.items()}
    if isinstance(value, list):
        return [stash_sub(v, stash) for v in value]
    return value


def lookup(resp: Any, path: str, stash: dict) -> Any:
    """Dotted-path lookup with numeric indices, \\. escapes and $stash."""
    if path in ("$body", ""):
        return resp
    cur = resp
    for raw in re.split(r"(?<!\\)\.", path):
        key = raw.replace("\\.", ".")
        key = stash_sub(key, stash)
        if isinstance(key, str) and key.startswith("$"):
            key = stash.get(key[1:], key)
        if isinstance(cur, list):
            cur = cur[int(key)]
        elif isinstance(cur, dict):
            if key not in cur and str(key) in cur:
                key = str(key)
            cur = cur[key]
        else:
            raise YamlTestFailure(
                f"cannot descend into {type(cur).__name__} at [{key}] "
                f"of path [{path}]")
    return cur


def values_match(expected: Any, actual: Any) -> bool:
    if isinstance(expected, str) and len(expected) > 1 \
            and expected.startswith("/") and expected.rstrip().endswith("/"):
        pattern = expected.strip().strip("/")
        return re.search(pattern, str(actual), re.VERBOSE) is not None
    if isinstance(expected, dict) and isinstance(actual, dict):
        return all(k in actual and values_match(v, actual[k])
                   for k, v in expected.items())
    if isinstance(expected, list) and isinstance(actual, list):
        return (len(expected) == len(actual)
                and all(values_match(e, a)
                        for e, a in zip(expected, actual)))
    if isinstance(expected, bool) or isinstance(actual, bool):
        return bool(expected) == bool(actual) \
            and isinstance(expected, type(actual))
    if isinstance(expected, (int, float)) and isinstance(actual, (int, float)):
        return float(expected) == float(actual)
    if isinstance(expected, (int, float)) and isinstance(actual, str):
        try:
            return float(expected) == float(actual)
        except ValueError:
            return False
    return expected == actual


# ----------------------------------------------------------------------
# Skip sections
# ----------------------------------------------------------------------


def _parse_version(s: str) -> Tuple[int, ...]:
    nums = re.findall(r"\d+", s)
    return tuple(int(n) for n in nums[:3]) if nums else (0, 0, 0)


def should_skip(skip: dict) -> Optional[str]:
    features = skip.get("features") or []
    if isinstance(features, str):
        features = [features]
    unsupported = [f for f in features if f not in SUPPORTED_FEATURES]
    if unsupported:
        return f"features {unsupported}"
    version = skip.get("version")
    if version:
        if str(version).strip().lower() == "all":
            return "version: all"
        m = re.match(r"\s*(\S*)\s*-\s*(\S*)\s*", str(version))
        if m:
            lo = _parse_version(m.group(1)) if m.group(1) else (0, 0, 0)
            hi = (_parse_version(m.group(2)) if m.group(2)
                  else (99, 99, 99))
            if lo <= ENGINE_VERSION <= hi:
                return f"version range {version}"
    return None


# ----------------------------------------------------------------------
# Runner
# ----------------------------------------------------------------------

CATCH_STATUS = {
    "missing": {404},
    "conflict": {409},
    "forbidden": {403},
    "unauthorized": {401},
    "request_timeout": {408},
    "bad_request": {400},
}


class YamlTestClient:
    """HTTP client against the engine's REST server."""

    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")

    def request(self, method: str, path: str, params: dict, body) -> Tuple[int, Any]:
        url = self.base_url + (path if path.startswith("/") else "/" + path)
        if params:
            def flat_one(v):
                if isinstance(v, bool):
                    return "true" if v else "false"  # not python's "True"
                if isinstance(v, (list, tuple)):
                    return ",".join(flat_one(x) for x in v)
                return str(v)

            url += "?" + urllib.parse.urlencode(
                {k: flat_one(v) for k, v in params.items()})
        data = None
        headers = {}
        if body is not None:
            if isinstance(body, (list, tuple)):
                # bulk-style: newline-delimited JSON; string elements are
                # already-serialized lines (bulk/20_list_of_strings.yml)
                data = ("\n".join(
                    x.strip() if isinstance(x, str) else json.dumps(x)
                    for x in body) + "\n").encode()
                headers["Content-Type"] = "application/x-ndjson"
            elif isinstance(body, str):
                data = body.encode()
                headers["Content-Type"] = "application/json"
            else:
                data = json.dumps(body).encode()
                headers["Content-Type"] = "application/json"
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req) as resp:
                raw = resp.read()
                status = resp.status
                ctype = resp.headers.get("Content-Type", "")
        except urllib.error.HTTPError as e:
            raw = e.read()
            status = e.code
            ctype = e.headers.get("Content-Type", "") if e.headers else ""
        if not raw:
            # cat/text endpoints legitimately return empty bodies the
            # tests regex-match against ^$
            return status, ""
        if "json" not in ctype:
            # text responses (cat API) must stay strings: "2\n" would
            # otherwise json-parse into a number and break regex matches
            return status, raw.decode("utf-8", "replace")
        try:
            return status, json.loads(raw)
        except json.JSONDecodeError:
            return status, raw.decode("utf-8", "replace")


class YamlTestRunner:
    def __init__(self, specs: ApiSpecs, client: YamlTestClient):
        self.specs = specs
        self.client = client

    # -- one file ------------------------------------------------------

    def run_file(self, path: str) -> List[str]:
        """Run every test doc in a YAML file. Returns the executed test
        names; raises YamlTestFailure on the first failing assertion and
        YamlTestSkipped if the whole file is skipped."""
        with open(path, encoding="utf-8") as f:
            docs = list(yaml.safe_load_all(f))
        setup_steps: List[dict] = []
        teardown_steps: List[dict] = []
        tests: List[Tuple[str, list]] = []
        for doc in docs:
            if not doc:
                continue
            for name, steps in doc.items():
                if name == "setup":
                    setup_steps = steps
                elif name == "teardown":
                    teardown_steps = steps
                else:
                    tests.append((name, steps))
        # file-level skip lives in the setup section
        for step in setup_steps:
            if "skip" in step:
                reason = should_skip(step["skip"])
                if reason:
                    raise YamlTestSkipped(f"setup skip: {reason}")
        executed = []
        for name, steps in tests:
            skip_reason = None
            for step in steps:
                if "skip" in step:
                    skip_reason = should_skip(step["skip"])
                    if skip_reason:
                        break
            if skip_reason:
                continue
            stash: Dict[str, Any] = {}
            try:
                for step in setup_steps:
                    self.run_step(step, stash, where=f"{name}/setup")
                for step in steps:
                    self.run_step(step, stash, where=name)
            finally:
                for step in teardown_steps:
                    try:
                        self.run_step(step, stash, where=f"{name}/teardown")
                    except Exception:
                        pass
                self.wipe()
            executed.append(name)
        return executed

    def wipe(self) -> None:
        """Reset cluster state between tests (the reference's
        wipeCluster): delete all indices and templates."""
        self.client.request("DELETE", "/*", {}, None)
        status, templates = self.client.request("GET", "/_template", {}, None)
        if status == 200 and isinstance(templates, dict):
            for name in templates:
                self.client.request("DELETE", f"/_template/{name}", {}, None)

    # -- steps ---------------------------------------------------------

    def run_step(self, step: dict, stash: dict, where: str) -> None:
        for kind, payload in step.items():
            if kind == "skip":
                continue
            handler = getattr(self, f"_step_{kind}", None)
            if handler is None:
                raise YamlTestFailure(f"[{where}] unsupported step [{kind}]")
            handler(payload, stash, where)

    def _step_do(self, payload: dict, stash: dict, where: str) -> None:
        payload = dict(payload)
        catch = payload.pop("catch", None)
        payload.pop("warnings", None)
        payload.pop("headers", None)
        if len(payload) != 1:
            raise YamlTestFailure(f"[{where}] do with {len(payload)} apis")
        (api_name, args), = payload.items()
        args = stash_sub(args or {}, stash)
        if api_name == "raw":
            # raw: {method, path, ...query params, body} — bypasses the
            # api specs (used for malformed-request tests)
            method = args.pop("method", "GET")
            path = "/" + str(args.pop("path", "")).lstrip("/")
            raw_body = args.pop("body", None)
            status, resp = self.client.request(method, path, args, raw_body)
            stash["__last_response"] = resp
            if catch is None:
                if status >= 400:
                    raise YamlTestFailure(
                        f"[{where}] raw {method} {path} failed "
                        f"[{status}]: {str(resp)[:200]}")
            elif catch.startswith("/") and catch.endswith("/"):
                if status < 400 or not re.search(catch.strip("/"),
                                                 json.dumps(resp)):
                    raise YamlTestFailure(
                        f"[{where}] raw expected error {catch}, got "
                        f"[{status}] {str(resp)[:200]}")
            elif catch in CATCH_STATUS:
                if status not in CATCH_STATUS[catch]:
                    raise YamlTestFailure(
                        f"[{where}] raw expected {catch} "
                        f"{CATCH_STATUS[catch]}, got [{status}]")
            elif status < 400:
                raise YamlTestFailure(
                    f"[{where}] raw expected error, got [{status}]")
            return
        # `ignore: 404` style client-side status suppression
        ignore = args.pop("ignore", None) if isinstance(args, dict) else None
        if ignore is not None and not isinstance(ignore, list):
            ignore = [ignore]
        try:
            method, path, params, body = self.specs.build_request(
                api_name, args)
        except YamlTestFailure:
            if catch == "param":
                # client-side request validation failure — exactly what
                # catch: param expects
                return
            raise
        status, resp = self.client.request(method, path, params, body)
        if method == "HEAD":
            # the reference runner exposes HEAD (exists-style) results as
            # a boolean body; a 404 is a legitimate "false", not an error
            stash["__last_response"] = status < 300
            if status in (200, 404):
                return
        stash["__last_response"] = resp
        if ignore and status in {int(i) for i in ignore}:
            return
        if catch is None:
            if status >= 400:
                raise YamlTestFailure(
                    f"[{where}] {api_name} failed [{status}]: "
                    f"{str(resp)[:400]}")
            return
        if catch.startswith("/") and catch.endswith("/"):
            if status < 400:
                raise YamlTestFailure(
                    f"[{where}] expected error matching {catch}, got "
                    f"[{status}]")
            if not re.search(catch.strip("/"), json.dumps(resp)):
                raise YamlTestFailure(
                    f"[{where}] error {str(resp)[:300]} !~ {catch}")
            return
        if catch == "param":
            # client-side validation errors surface as 400s here
            if status < 400:
                raise YamlTestFailure(f"[{where}] expected param error")
            return
        if catch == "request":
            if status < 400:
                raise YamlTestFailure(
                    f"[{where}] expected request error, got [{status}]")
            return
        expected = CATCH_STATUS.get(catch)
        if expected is None:
            raise YamlTestFailure(f"[{where}] unknown catch [{catch}]")
        if status not in expected:
            raise YamlTestFailure(
                f"[{where}] expected {catch} {expected}, got [{status}]: "
                f"{str(resp)[:300]}")

    def _last(self, stash: dict):
        return stash.get("__last_response")

    def _step_match(self, payload: dict, stash: dict, where: str) -> None:
        for path, expected in payload.items():
            expected = stash_sub(expected, stash)
            try:
                actual = lookup(self._last(stash), path, stash)
            except (KeyError, IndexError, TypeError) as e:
                raise YamlTestFailure(
                    f"[{where}] match {path}: path missing ({e!r})"
                ) from None
            if not values_match(expected, actual):
                raise YamlTestFailure(
                    f"[{where}] match {path}: expected {expected!r}, "
                    f"got {actual!r}")

    def _step_length(self, payload: dict, stash: dict, where: str) -> None:
        for path, expected in payload.items():
            actual = lookup(self._last(stash), path, stash)
            if len(actual) != int(stash_sub(expected, stash)):
                raise YamlTestFailure(
                    f"[{where}] length {path}: expected {expected}, "
                    f"got {len(actual)}")

    def _step_set(self, payload: dict, stash: dict, where: str) -> None:
        for path, var in payload.items():
            stash[var] = lookup(self._last(stash), path, stash)

    @staticmethod
    def _is_falsy(val) -> bool:
        """Reference falsiness: null/false/""/"false"/0 only — an empty
        object or list IS true (put-template alias bodies are {})."""
        return val is None or val is False or val in ("", "false") or (
            isinstance(val, (int, float)) and not isinstance(val, bool)
            and val == 0)

    def _step_is_true(self, payload, stash: dict, where: str) -> None:
        try:
            val = lookup(self._last(stash), payload, stash)
        except (KeyError, IndexError, YamlTestFailure):
            val = None
        if self._is_falsy(val):
            raise YamlTestFailure(f"[{where}] is_true {payload}: {val!r}")

    def _step_is_false(self, payload, stash: dict, where: str) -> None:
        try:
            val = lookup(self._last(stash), payload, stash)
        except (KeyError, IndexError, YamlTestFailure):
            val = None
        if not self._is_falsy(val):
            raise YamlTestFailure(f"[{where}] is_false {payload}: {val!r}")

    def _cmp(self, payload: dict, stash: dict, where: str, op, name) -> None:
        for path, expected in payload.items():
            expected = stash_sub(expected, stash)
            try:
                actual = lookup(self._last(stash), path, stash)
            except (KeyError, IndexError, TypeError) as e:
                raise YamlTestFailure(
                    f"[{where}] {name} {path}: path missing ({e!r})"
                ) from None
            if not op(float(actual), float(expected)):
                raise YamlTestFailure(
                    f"[{where}] {name} {path}: {actual!r} vs {expected!r}")

    def _step_gt(self, payload, stash, where):
        self._cmp(payload, stash, where, lambda a, b: a > b, "gt")

    def _step_gte(self, payload, stash, where):
        self._cmp(payload, stash, where, lambda a, b: a >= b, "gte")

    def _step_lt(self, payload, stash, where):
        self._cmp(payload, stash, where, lambda a, b: a < b, "lt")

    def _step_lte(self, payload, stash, where):
        self._cmp(payload, stash, where, lambda a, b: a <= b, "lte")
