"""Adaptive replica selection: rank shard copies by observed performance.

Role model: ``ResponseCollectorService`` (reference:
core/src/main/java/org/elasticsearch/node/ResponseCollectorService.java) —
the coordinator keeps an EWMA of each node's response time (and queue
size) and ranks copies so reads route to the historically fastest copy
instead of always primary-first (the C3 algorithm, simplified here to the
latency term: queue sizes don't exist in the in-process transport).
"""

from __future__ import annotations

import threading
from typing import Dict, List

ALPHA = 0.3  # EWMA smoothing (reference: QueueResizingEsThreadPoolExecutor)


class ResponseCollectorService:
    def __init__(self):
        self._ewma: Dict[str, float] = {}
        self._outstanding: Dict[str, int] = {}
        self._lock = threading.Lock()

    def add_response_time(self, node_id: str, seconds: float) -> None:
        with self._lock:
            prev = self._ewma.get(node_id)
            self._ewma[node_id] = (seconds if prev is None
                                   else ALPHA * seconds + (1 - ALPHA) * prev)

    def on_send(self, node_id: str) -> None:
        with self._lock:
            self._outstanding[node_id] = self._outstanding.get(node_id, 0) + 1

    def on_complete(self, node_id: str) -> None:
        with self._lock:
            n = self._outstanding.get(node_id, 1)
            self._outstanding[node_id] = max(0, n - 1)

    def on_failure(self, node_id: str, seconds: float = 0.0) -> None:
        """A failed or timed-out request PENALIZES the node's rank:
        double its EWMA (floored at the observed wasted time and 100ms)
        so a node that keeps timing out stops being preferred — but is
        never rewarded with a better rank by an instant connection
        error. Successes recover the rank through the normal EWMA."""
        with self._lock:
            prev = self._ewma.get(node_id, 0.0)
            self._ewma[node_id] = max(prev * 2.0, float(seconds), 0.1)

    def rank(self, node_id: str) -> float:
        """Lower is better. Unknown nodes rank best so they get probed
        (the reference seeds unknown nodes optimistically)."""
        with self._lock:
            ewma = self._ewma.get(node_id, 0.0)
            return ewma * (1.0 + self._outstanding.get(node_id, 0))

    def stats(self) -> Dict[str, dict]:
        with self._lock:
            return {n: {"avg_response_time_ns": int(v * 1e9),
                        "outstanding": self._outstanding.get(n, 0)}
                    for n, v in self._ewma.items()}

    def order_copies(self, copies: List, tiebreak_primary_first: bool = True) -> List:
        """Order shard copies by rank; ties keep primary first (stable)."""
        return sorted(copies, key=lambda c: (
            self.rank(c.node_id),
            (not c.primary) if tiebreak_primary_first else 0,
            c.node_id,
        ))
