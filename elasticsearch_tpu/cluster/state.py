"""Cluster state: immutable snapshot of metadata + routing.

Role model: ``ClusterState`` (core/.../cluster/ClusterState.java) with
``MetaData``/``IndexMetaData`` (settings, mappings, aliases per index) and
``RoutingTable`` (shard copies + their states). State transitions go
through ``ClusterService.submit_state_update_task`` — a single-threaded
master queue exactly like MasterService.runTasks (cluster/service/
MasterService.java:178) — and appliers observe the new state
(ClusterApplierService).

Single-node deployment: this node is always the elected master (the
reference's SingleNodeDiscovery, discovery/single/SingleNodeDiscovery.java:48).
The multi-host path keeps these shapes and publishes diffs over DCN.
"""

from __future__ import annotations

import copy
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from elasticsearch_tpu.common.errors import IndexNotFoundException
from elasticsearch_tpu.common.settings import Settings


class ShardRoutingState:
    UNASSIGNED = "UNASSIGNED"
    INITIALIZING = "INITIALIZING"
    STARTED = "STARTED"
    RELOCATING = "RELOCATING"


@dataclass
class ShardRouting:
    index: str
    shard_id: int
    node_id: Optional[str]
    primary: bool
    state: str = ShardRoutingState.STARTED
    # explicit relocation link (RELOCATING source -> target node): the
    # allocator retires the source only when THIS node's copy has
    # started, never some other same-role peer (reference:
    # ShardRouting.relocatingNodeId)
    relocating_to: Optional[str] = None

    def to_dict(self) -> dict:
        d = {
            "index": self.index,
            "shard": self.shard_id,
            "node": self.node_id,
            "primary": self.primary,
            "state": self.state,
        }
        if self.relocating_to is not None:
            d["relocating_node"] = self.relocating_to
        return d


@dataclass
class IndexMetadata:
    name: str
    settings: Settings
    mappings: dict
    aliases: Dict[str, dict] = field(default_factory=dict)
    state: str = "open"  # open | close
    creation_date: int = 0
    version: int = 1

    @property
    def num_shards(self) -> int:
        return self.settings.get_int("index.number_of_shards", 1)

    @property
    def num_replicas(self) -> int:
        return self.settings.get_int("index.number_of_replicas", 1)

    def to_dict(self) -> dict:
        return {
            "settings": self.settings.as_nested_dict(),
            "mappings": {"_doc": self.mappings},
            "aliases": self.aliases,
            "state": self.state,
        }


@dataclass
class DiscoveryNode:
    node_id: str
    name: str
    address: str
    roles: tuple = ("master", "data", "ingest")

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "transport_address": self.address,
            "roles": list(self.roles),
        }


class ClusterState:
    """Immutable-by-convention snapshot; builders copy."""

    def __init__(self, cluster_name: str, version: int = 0,
                 indices: Optional[Dict[str, IndexMetadata]] = None,
                 nodes: Optional[Dict[str, DiscoveryNode]] = None,
                 master_node_id: Optional[str] = None,
                 templates: Optional[Dict[str, dict]] = None,
                 persistent_settings: Optional[Settings] = None,
                 transient_settings: Optional[Settings] = None,
                 stored_scripts: Optional[Dict[str, dict]] = None,
                 ingest_pipelines: Optional[Dict[str, dict]] = None,
                 repositories: Optional[Dict[str, dict]] = None,
                 routing: Optional[dict] = None):
        self.cluster_name = cluster_name
        self.version = version
        self.indices = dict(indices or {})
        self.nodes = dict(nodes or {})
        self.master_node_id = master_node_id
        self.templates = dict(templates or {})
        self.persistent_settings = persistent_settings or Settings.EMPTY
        self.transient_settings = transient_settings or Settings.EMPTY
        self.stored_scripts = dict(stored_scripts or {})
        self.ingest_pipelines = dict(ingest_pipelines or {})
        self.repositories = dict(repositories or {})
        # explicit routing table ({index: {shard_id: [ShardRouting]}}),
        # set by reroute/allocation; None = synthesize from metadata
        # (single-node: every primary on the master)
        self.routing = routing

    def copy(self, **overrides) -> "ClusterState":
        kw = dict(
            cluster_name=self.cluster_name,
            version=self.version + 1,
            indices=copy.deepcopy(self.indices),
            nodes=dict(self.nodes),
            master_node_id=self.master_node_id,
            templates=copy.deepcopy(self.templates),
            persistent_settings=self.persistent_settings,
            transient_settings=self.transient_settings,
            stored_scripts=dict(self.stored_scripts),
            ingest_pipelines=copy.deepcopy(self.ingest_pipelines),
            repositories=copy.deepcopy(self.repositories),
            routing=copy.deepcopy(self.routing),
        )
        kw.update(overrides)
        return ClusterState(**kw)

    def index_metadata(self, name: str) -> IndexMetadata:
        md = self.indices.get(name)
        if md is None:
            raise IndexNotFoundException(name)
        return md

    def resolve_index_names(self, expression: str) -> List[str]:
        """Index-name expression resolution: names, aliases, wildcards,
        comma lists, _all (cluster/metadata/IndexNameExpressionResolver)."""
        import fnmatch

        if expression in ("_all", "*", "", None):
            return sorted(self.indices)
        out: List[str] = []
        for part in str(expression).split(","):
            part = part.strip()
            if not part:
                continue
            matched = False
            if "*" in part:
                for name, md in sorted(self.indices.items()):
                    if fnmatch.fnmatchcase(name, part) or any(
                        fnmatch.fnmatchcase(a, part) for a in md.aliases
                    ):
                        out.append(name)
                        matched = True
            else:
                if part in self.indices:
                    out.append(part)
                    matched = True
                else:
                    for name, md in sorted(self.indices.items()):
                        if part in md.aliases:
                            out.append(name)
                            matched = True
            if not matched and "*" not in part:
                raise IndexNotFoundException(part)
        seen, uniq = set(), []
        for n in out:
            if n not in seen:
                seen.add(n)
                uniq.append(n)
        return uniq

    def routing_table(self) -> Dict[str, List[ShardRouting]]:
        table = {}
        for name, md in self.indices.items():
            shards = []
            for sid in range(md.num_shards):
                shards.append(ShardRouting(name, sid, self.master_node_id, True))
            table[name] = shards
        return table

    def to_dict(self) -> dict:
        return {
            "cluster_name": self.cluster_name,
            "version": self.version,
            "master_node": self.master_node_id,
            "nodes": {nid: n.to_dict() for nid, n in self.nodes.items()},
            "metadata": {
                "indices": {n: md.to_dict() for n, md in self.indices.items()},
                "templates": self.templates,
                "cluster_settings": {
                    "persistent": self.persistent_settings.as_nested_dict(),
                    "transient": self.transient_settings.as_nested_dict(),
                },
            },
            "routing_table": {"indices": self._routing_table_dict()},
        }

    def _routing_table_dict(self) -> dict:
        """Render the routing table against CURRENT metadata: the explicit
        table (reroute/allocation) is a per-index overlay — indices
        created after the last reroute synthesize their default routing,
        deleted indices drop out (the table must never freeze)."""
        explicit = self.routing or {}
        out = {}
        for n, shards in self.routing_table().items():
            if n in explicit:
                out[n] = {"shards": {
                    str(sid): [c.to_dict() for c in copies]
                    for sid, copies in explicit[n].items()}}
            else:
                out[n] = {"shards": {str(s.shard_id): [s.to_dict()]
                                     for s in shards}}
        return out


class ClusterService:
    """Single-threaded state-update queue + applier dispatch.

    submit_state_update_task(source, fn) where fn(state) -> new state;
    appliers/listeners run after each successful update (the two-phase
    publish degenerates to local apply on a single node)."""

    def __init__(self, initial_state: ClusterState):
        self._state = initial_state
        self._lock = threading.Lock()
        self._appliers: List[Callable[[ClusterState, ClusterState], None]] = []
        self._listeners: List[Callable[[ClusterState], None]] = []

    @property
    def state(self) -> ClusterState:
        return self._state

    def add_applier(self, applier: Callable[[ClusterState, ClusterState], None]) -> None:
        self._appliers.append(applier)

    def add_listener(self, listener: Callable[[ClusterState], None]) -> None:
        self._listeners.append(listener)

    def submit_state_update_task(self, source: str,
                                 update: Callable[[ClusterState], ClusterState]):
        """Runs the task under the master lock; appliers see old+new."""
        with self._lock:
            old = self._state
            new = update(old)
            if new is old:
                return old
            self._state = new
        for applier in self._appliers:
            applier(old, new)
        for listener in self._listeners:
            listener(new)
        return new


def cluster_health(state: ClusterState, indices_service=None) -> dict:
    """_cluster/health (action/admin/cluster/health): single-node => all
    primaries active, replicas unassignable => yellow unless replicas=0."""
    n_shards = sum(md.num_shards for md in state.indices.values()
                   if md.state == "open")
    unassigned = sum(
        md.num_shards * md.num_replicas for md in state.indices.values()
        if md.state == "open"
    )
    status = "green" if unassigned == 0 else "yellow"
    total = n_shards + unassigned
    return {
        "cluster_name": state.cluster_name,
        "status": status,
        "timed_out": False,
        "number_of_nodes": len(state.nodes),
        "number_of_data_nodes": len(state.nodes),
        "active_primary_shards": n_shards,
        "active_shards": n_shards,
        "relocating_shards": 0,
        "initializing_shards": 0,
        "unassigned_shards": unassigned,
        "delayed_unassigned_shards": 0,
        "number_of_pending_tasks": 0,
        "number_of_in_flight_fetch": 0,
        "task_max_waiting_in_queue_millis": 0,
        "active_shards_percent_as_number": (
            100.0 * n_shards / total if total else 100.0
        ),
    }
