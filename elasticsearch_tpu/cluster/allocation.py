"""Shard allocation: assign primaries and replicas to data nodes.

Role model: ``AllocationService`` + ``BalancedShardsAllocator`` + deciders
(cluster/routing/allocation/). Deciders implemented:
SameShardAllocationDecider (a replica never lands on its primary's node),
balance-by-count, ``DiskThresholdDecider`` (low watermark blocks new
allocations, high watermark moves replicas off — fed by per-node disk
usage, the ``ClusterInfoService``/``DiskThresholdMonitor`` analog), and
``AwarenessAllocationDecider`` (spread copies across configured node
attribute values, e.g. zones). Assignments are sticky: existing placements
survive reroutes while their node is alive (the reference's "prefer
existing allocation").
"""

from __future__ import annotations

from typing import Dict, List, Optional

from elasticsearch_tpu.cluster.state import ShardRouting, ShardRoutingState

# routing table shape: {index: {shard_id: [ShardRouting, ...]}} — first
# entry with primary=True is the primary copy.
RoutingTable = Dict[str, Dict[int, List[ShardRouting]]]


def _node_load(table: RoutingTable) -> Dict[str, int]:
    load: Dict[str, int] = {}
    for shards in table.values():
        for copies in shards.values():
            for c in copies:
                if c.node_id is not None:
                    load[c.node_id] = load.get(c.node_id, 0) + 1
    return load


def _least_loaded(candidates: List[str], load: Dict[str, int]) -> Optional[str]:
    if not candidates:
        return None
    return min(candidates, key=lambda n: (load.get(n, 0), n))


# DiskThresholdDecider defaults (cluster.routing.allocation.disk.watermark.*)
WATERMARK_LOW = 0.85
WATERMARK_HIGH = 0.90


def _pick_node(candidates: List[str], load: Dict[str, int],
               existing_copies: List[ShardRouting],
               node_info: Optional[Dict[str, dict]],
               awareness_attributes: Optional[List[str]],
               watermark_low: float) -> Optional[str]:
    """Decider chain for one unassigned copy: disk low-watermark filter,
    awareness-attribute preference, then least-loaded."""
    if node_info:
        ok = [n for n in candidates
              if (node_info.get(n, {}).get("disk") or 0.0) < watermark_low]
        if ok:
            candidates = ok  # else: ignore the watermark rather than leave
            # the copy unassigned? No — the reference leaves it unassigned.
        else:
            return None
    if not candidates:
        return None
    if awareness_attributes and node_info:
        def attr_penalty(n: str) -> int:
            # count existing copies sharing any awareness value with n
            my = node_info.get(n, {}).get("attrs") or {}
            penalty = 0
            for attr in awareness_attributes:
                mine = my.get(attr)
                if mine is None:
                    continue
                for c in existing_copies:
                    other = (node_info.get(c.node_id, {}).get("attrs") or {})
                    if other.get(attr) == mine:
                        penalty += 1
            return penalty

        penalties = {n: attr_penalty(n) for n in candidates}
        best_penalty = min(penalties.values())
        candidates = [n for n in candidates if penalties[n] == best_penalty]
    return _least_loaded(candidates, load)


def allocate(indices_meta: Dict, data_nodes: List[str],
             previous: Optional[RoutingTable] = None,
             node_info: Optional[Dict[str, dict]] = None,
             awareness_attributes: Optional[List[str]] = None,
             watermark_low: float = WATERMARK_LOW,
             watermark_high: float = WATERMARK_HIGH) -> RoutingTable:
    """Compute the routing table for the current node set.

    indices_meta: {name: IndexMetadata}. Copies on departed nodes are
    dropped; a surviving replica is promoted when its primary is gone
    (primary promotion — ShardStateAction/failShard path, SURVEY §5.3);
    unassigned copies fill onto the least-loaded eligible node.
    node_info: {node_id: {"attrs": {...}, "disk": used_fraction}} — feeds
    the disk-threshold + awareness deciders.
    """
    previous = previous or {}
    alive = set(data_nodes)
    # DiskThresholdMonitor: nodes above the high watermark shed replicas —
    # but only onto an eligible target (a healthy in-sync copy is never
    # discarded without a replacement)
    hot = set()
    if node_info:
        hot = {n for n in alive
               if (node_info.get(n, {}).get("disk") or 0.0) >= watermark_high}
    table: RoutingTable = {}
    for name, md in indices_meta.items():
        if md.state != "open":
            table[name] = {}
            continue
        shards: Dict[int, List[ShardRouting]] = {}
        prev_shards = previous.get(name, {})
        for sid in range(md.num_shards):
            all_prev = prev_shards.get(sid, [])
            prev_copies = [c for c in all_prev if c.node_id in alive]
            primary = next((c for c in prev_copies if c.primary), None)
            replicas = [c for c in prev_copies if not c.primary]
            if primary is None and all_prev:
                # promote a STARTED replica only (the in-sync set
                # analog): an INITIALIZING survivor may hold a partial
                # recovery — promoting it would serve stale data
                # silently; the reference refuses via in-sync allocation
                # ids
                started = [r for r in replicas
                           if r.state == ShardRoutingState.STARTED]
                if started:
                    promo = started[0]
                    replicas.remove(promo)
                    promo.primary = True
                    primary = promo
                else:
                    # no in-sync survivor: RETAIN the departed primary
                    # copy in the table. The shard stays red (the fill
                    # below sees a primary and will not allocate a fresh
                    # empty one over lost data), and if the node comes
                    # back its copy resumes with its data — the
                    # reference's delayed-allocation / node-rejoin path
                    primary = next(
                        (c for c in all_prev
                         if c.primary and c.node_id not in alive), None)
            copies: List[ShardRouting] = []
            if primary is not None:
                copies.append(primary)
            copies.extend(replicas)
            shards[sid] = copies
        table[name] = shards

    load = _node_load(table)
    # fill unassigned primaries first, then replicas
    for name, md in indices_meta.items():
        if md.state != "open":
            continue
        for sid in range(md.num_shards):
            copies = table[name][sid]
            if not any(c.primary for c in copies):
                # reached only when the shard never had copies (fresh
                # index / previously unplaceable): a shard that LOST its
                # data keeps its departed primary routed above, so it
                # stays red instead of restarting empty
                node = _pick_node(list(alive), load, copies, node_info,
                                  awareness_attributes, watermark_low)
                if node is not None:
                    copies.insert(0, ShardRouting(
                        name, sid, node, True, ShardRoutingState.INITIALIZING
                    ))
                    load[node] = load.get(node, 0) + 1
    for name, md in indices_meta.items():
        if md.state != "open":
            continue
        for sid in range(md.num_shards):
            copies = table[name][sid]
            while len(copies) < 1 + md.num_replicas:
                used = {c.node_id for c in copies}
                candidates = [n for n in alive if n not in used]
                node = _pick_node(candidates, load, copies, node_info,
                                  awareness_attributes, watermark_low)
                if node is None:
                    break  # not enough nodes — stays unassigned (yellow)
                copies.append(ShardRouting(
                    name, sid, node, False, ShardRoutingState.INITIALIZING
                ))
                load[node] = load.get(node, 0) + 1
    if hot:
        _relocate_hot_replicas(table, alive, load, node_info,
                               awareness_attributes, watermark_low, hot,
                               indices_meta)
    # cancel surplus relocation targets whose reason went away (the hot
    # source cooled down before the replacement finished)
    for name, md in indices_meta.items():
        if md.state != "open":
            continue
        desired = 1 + md.num_replicas
        for copies in table[name].values():
            if len(copies) <= desired:
                continue
            awaiting = any(c.node_id in hot and not c.primary for c in copies)
            if awaiting:
                continue  # relocation in progress: keep source + target
            for c in list(copies):
                if len(copies) <= desired:
                    break
                if not c.primary and c.state == ShardRoutingState.INITIALIZING:
                    copies.remove(c)
                    load[c.node_id] = load.get(c.node_id, 1) - 1
    _rebalance_replicas(table, alive, load, node_info, awareness_attributes,
                        watermark_low)
    return table


def _relocate_hot_replicas(table: RoutingTable, alive: set,
                           load: Dict[str, int], node_info, awareness,
                           watermark_low: float, hot: set,
                           indices_meta: Dict) -> None:
    """Move replicas off high-watermark nodes when (and only when) a
    target under the low watermark exists. A STARTED (data-bearing) source
    stays until its replacement has started — relocation keeps both copies
    live like the reference's RELOCATING state; only empty INITIALIZING
    copies move directly."""
    for index, shards in table.items():
        desired_replicas = indices_meta[index].num_replicas
        for copies in shards.values():
            # phase 1: a replacement started — retire the hot source
            healthy_started = [c for c in copies
                               if not c.primary
                               and c.state == ShardRoutingState.STARTED
                               and c.node_id not in hot]
            for c in list(copies):
                if (not c.primary and c.node_id in hot
                        and len(healthy_started) >= desired_replicas):
                    copies.remove(c)
                    load[c.node_id] = load.get(c.node_id, 1) - 1
            # phase 2: spawn replacements / move empty copies
            for copy in list(copies):
                if copy.primary or copy.node_id not in hot:
                    continue
                used = {c.node_id for c in copies if c is not copy}
                candidates = [n for n in alive if n not in used]
                target = _pick_node(candidates, load,
                                    [c for c in copies if c is not copy],
                                    node_info, awareness, watermark_low)
                if target is None or target == copy.node_id:
                    continue
                if copy.state == ShardRoutingState.INITIALIZING:
                    # empty copy: move it outright
                    load[copy.node_id] = load.get(copy.node_id, 1) - 1
                    load[target] = load.get(target, 0) + 1
                    copy.node_id = target
                else:
                    # data-bearing copy: add the target alongside; the
                    # source retires on a later reroute once it starts
                    copies.append(ShardRouting(
                        copy.index, copy.shard_id, target, False,
                        ShardRoutingState.INITIALIZING))
                    load[target] = load.get(target, 0) + 1


def _rebalance_replicas(table: RoutingTable, alive: set,
                        load: Dict[str, int],
                        node_info: Optional[Dict[str, dict]] = None,
                        awareness_attributes: Optional[List[str]] = None,
                        watermark_low: float = WATERMARK_LOW) -> None:
    """Move freshly-assigned (INITIALIZING) replicas off overloaded nodes —
    the greedy fill can pile ties onto one node (BalancedShardsAllocator's
    balancing step). Started replicas are never moved here (moving them
    costs a recovery; rebalancing of started copies is a later round)."""
    improved = True
    while improved:
        improved = False
        for shards in table.values():
            for copies in shards.values():
                for copy in copies:
                    if copy.primary or copy.state != ShardRoutingState.INITIALIZING:
                        continue
                    used = {c.node_id for c in copies if c is not copy}
                    candidates = [n for n in alive if n not in used]
                    best = _pick_node(candidates, load,
                                      [c for c in copies if c is not copy],
                                      node_info, awareness_attributes,
                                      watermark_low)
                    if best is not None and copy.node_id is not None and \
                            load.get(best, 0) + 1 < load.get(copy.node_id, 0):
                        load[copy.node_id] -= 1
                        load[best] = load.get(best, 0) + 1
                        copy.node_id = best
                        improved = True


def routing_to_dict(table: RoutingTable) -> dict:
    return {
        name: {
            str(sid): [c.to_dict() for c in copies]
            for sid, copies in shards.items()
        }
        for name, shards in table.items()
    }


def routing_from_dict(d: dict) -> RoutingTable:
    out: RoutingTable = {}
    for name, shards in d.items():
        out[name] = {}
        for sid, copies in shards.items():
            out[name][int(sid)] = [
                ShardRouting(c["index"], c["shard"], c["node"], c["primary"],
                             c["state"])
                for c in copies
            ]
    return out
