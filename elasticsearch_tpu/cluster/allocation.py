"""Shard allocation: assign primaries and replicas to data nodes.

Role model: ``AllocationService`` + ``BalancedShardsAllocator`` + deciders
(cluster/routing/allocation/). Deciders implemented:
SameShardAllocationDecider (a replica never lands on its primary's node),
balance-by-count, ``DiskThresholdDecider`` (low watermark blocks new
allocations, high watermark moves replicas off — fed by per-node disk
usage, the ``ClusterInfoService``/``DiskThresholdMonitor`` analog), and
``AwarenessAllocationDecider`` (spread copies across configured node
attribute values, e.g. zones). Assignments are sticky: existing placements
survive reroutes while their node is alive (the reference's "prefer
existing allocation").
"""

from __future__ import annotations

from typing import Dict, List, Optional

from elasticsearch_tpu.cluster.state import ShardRouting, ShardRoutingState

# routing table shape: {index: {shard_id: [ShardRouting, ...]}} — first
# entry with primary=True is the primary copy.
RoutingTable = Dict[str, Dict[int, List[ShardRouting]]]


def _node_load(table: RoutingTable) -> Dict[str, int]:
    load: Dict[str, int] = {}
    for shards in table.values():
        for copies in shards.values():
            for c in copies:
                if c.node_id is not None:
                    load[c.node_id] = load.get(c.node_id, 0) + 1
    return load


def _least_loaded(candidates: List[str], load: Dict[str, int]) -> Optional[str]:
    if not candidates:
        return None
    return min(candidates, key=lambda n: (load.get(n, 0), n))


# DiskThresholdDecider defaults (cluster.routing.allocation.disk.watermark.*)
WATERMARK_LOW = 0.85
WATERMARK_HIGH = 0.90


def _pick_node(candidates: List[str], load: Dict[str, int],
               existing_copies: List[ShardRouting],
               node_info: Optional[Dict[str, dict]],
               awareness_attributes: Optional[List[str]],
               watermark_low: float) -> Optional[str]:
    """Decider chain for one unassigned copy: disk low-watermark filter,
    awareness-attribute preference, then least-loaded."""
    if node_info:
        ok = [n for n in candidates
              if (node_info.get(n, {}).get("disk") or 0.0) < watermark_low]
        if ok:
            candidates = ok  # else: ignore the watermark rather than leave
            # the copy unassigned? No — the reference leaves it unassigned.
        else:
            return None
    if not candidates:
        return None
    if awareness_attributes and node_info:
        def attr_penalty(n: str) -> int:
            # count existing copies sharing any awareness value with n
            my = node_info.get(n, {}).get("attrs") or {}
            penalty = 0
            for attr in awareness_attributes:
                mine = my.get(attr)
                if mine is None:
                    continue
                for c in existing_copies:
                    other = (node_info.get(c.node_id, {}).get("attrs") or {})
                    if other.get(attr) == mine:
                        penalty += 1
            return penalty

        penalties = {n: attr_penalty(n) for n in candidates}
        best_penalty = min(penalties.values())
        candidates = [n for n in candidates if penalties[n] == best_penalty]
    return _least_loaded(candidates, load)


def allocate(indices_meta: Dict, data_nodes: List[str],
             previous: Optional[RoutingTable] = None,
             node_info: Optional[Dict[str, dict]] = None,
             awareness_attributes: Optional[List[str]] = None,
             watermark_low: float = WATERMARK_LOW,
             watermark_high: float = WATERMARK_HIGH,
             no_fresh_primary: Optional[set] = None) -> RoutingTable:
    """Compute the routing table for the current node set.

    indices_meta: {name: IndexMetadata}. Copies on departed nodes are
    dropped; a surviving replica is promoted when its primary is gone
    (primary promotion — ShardStateAction/failShard path, SURVEY §5.3);
    unassigned copies fill onto the least-loaded eligible node.
    node_info: {node_id: {"attrs": {...}, "disk": used_fraction}} — feeds
    the disk-threshold + awareness deciders.
    no_fresh_primary: (index, sid) keys that must NEVER receive a fresh
    empty primary (ISSUE 16 corruption quarantine: the shard HAD data —
    its last copy is corrupt-retained — so filling an empty primary
    would be silent data-loss resurrection; the shard stays red until a
    verified copy returns via snapshot restore or marker repair).
    """
    no_fresh_primary = no_fresh_primary or set()
    previous = previous or {}
    alive = set(data_nodes)
    # DiskThresholdMonitor: nodes above the high watermark shed replicas —
    # but only onto an eligible target (a healthy in-sync copy is never
    # discarded without a replacement)
    hot = set()
    if node_info:
        hot = {n for n in alive
               if (node_info.get(n, {}).get("disk") or 0.0) >= watermark_high}
    table: RoutingTable = {}
    for name, md in indices_meta.items():
        if md.state != "open":
            table[name] = {}
            continue
        shards: Dict[int, List[ShardRouting]] = {}
        prev_shards = previous.get(name, {})
        for sid in range(md.num_shards):
            all_prev = prev_shards.get(sid, [])
            prev_copies = [c for c in all_prev if c.node_id in alive]
            primaries = [c for c in prev_copies if c.primary]
            primary = primaries[0] if primaries else None
            # a relocating primary's target carries primary=True too —
            # it must survive the rebuild alongside the source
            extra_primaries = primaries[1:]
            replicas = [c for c in prev_copies if not c.primary]
            if primary is None and all_prev:
                # promote a STARTED replica only (the in-sync set
                # analog): an INITIALIZING survivor may hold a partial
                # recovery — promoting it would serve stale data
                # silently; the reference refuses via in-sync allocation
                # ids
                started = [r for r in replicas
                           if r.state == ShardRoutingState.STARTED]
                if started:
                    promo = started[0]
                    replicas.remove(promo)
                    promo.primary = True
                    primary = promo
                else:
                    # no in-sync survivor: RETAIN the departed primary
                    # copy in the table. The shard stays red (the fill
                    # below sees a primary and will not allocate a fresh
                    # empty one over lost data), and if the node comes
                    # back its copy resumes with its data — the
                    # reference's delayed-allocation / node-rejoin path
                    primary = next(
                        (c for c in all_prev
                         if c.primary and c.node_id not in alive), None)
            copies: List[ShardRouting] = []
            if primary is not None:
                copies.append(primary)
            copies.extend(extra_primaries)
            copies.extend(replicas)
            shards[sid] = copies
        table[name] = shards

    # retire completed relocations: a RELOCATING source whose LINKED
    # target (relocating_to) has STARTED hands off and leaves the table
    # (the reference's relocation completion). The explicit link matters:
    # with 2+ replicas, some other started same-role peer must not
    # retire a source whose own target is still recovering.
    for name, shards in table.items():
        for copies in shards.values():
            for c in list(copies):
                if c.state != ShardRoutingState.RELOCATING:
                    continue
                target = next(
                    (o for o in copies
                     if o is not c and o.node_id == c.relocating_to), None)
                if target is None:
                    # target vanished (node left / cancelled): resume as
                    # a normal started copy
                    c.state = ShardRoutingState.STARTED
                    c.relocating_to = None
                elif target.state == ShardRoutingState.STARTED:
                    copies.remove(c)

    load = _node_load(table)
    # fill unassigned primaries first, then replicas
    for name, md in indices_meta.items():
        if md.state != "open":
            continue
        for sid in range(md.num_shards):
            copies = table[name][sid]
            if not any(c.primary for c in copies):
                if (name, sid) in no_fresh_primary:
                    # corrupt-retained last copy (ISSUE 16): the shard
                    # had data — an empty primary here would resurrect
                    # the index over lost bytes. Stays red/unassigned.
                    continue
                # reached only when the shard never had copies (fresh
                # index / previously unplaceable): a shard that LOST its
                # data keeps its departed primary routed above, so it
                # stays red instead of restarting empty
                node = _pick_node(list(alive), load, copies, node_info,
                                  awareness_attributes, watermark_low)
                if node is not None:
                    copies.insert(0, ShardRouting(
                        name, sid, node, True, ShardRoutingState.INITIALIZING
                    ))
                    load[node] = load.get(node, 0) + 1
    for name, md in indices_meta.items():
        if md.state != "open":
            continue
        for sid in range(md.num_shards):
            copies = table[name][sid]
            while len(copies) < 1 + md.num_replicas:
                used = {c.node_id for c in copies}
                candidates = [n for n in alive if n not in used]
                node = _pick_node(candidates, load, copies, node_info,
                                  awareness_attributes, watermark_low)
                if node is None:
                    break  # not enough nodes — stays unassigned (yellow)
                copies.append(ShardRouting(
                    name, sid, node, False, ShardRoutingState.INITIALIZING
                ))
                load[node] = load.get(node, 0) + 1
    if hot:
        _relocate_hot_replicas(table, alive, load, node_info,
                               awareness_attributes, watermark_low, hot,
                               indices_meta)
    # cancel surplus relocation targets whose reason went away (the hot
    # source cooled down before the replacement finished)
    for name, md in indices_meta.items():
        if md.state != "open":
            continue
        desired = 1 + md.num_replicas
        for copies in table[name].values():
            if len(copies) <= desired:
                continue
            awaiting = any(c.node_id in hot and not c.primary for c in copies)
            if awaiting:
                continue  # relocation in progress: keep source + target
            if any(c.state == ShardRoutingState.RELOCATING for c in copies):
                # an explicit move in progress (reroute command): the
                # source+target pair intentionally exceeds the desired
                # copy count until the handoff retires the source
                continue
            for c in list(copies):
                if len(copies) <= desired:
                    break
                if not c.primary and c.state == ShardRoutingState.INITIALIZING:
                    copies.remove(c)
                    load[c.node_id] = load.get(c.node_id, 1) - 1
    _rebalance_replicas(table, alive, load, node_info, awareness_attributes,
                        watermark_low)
    return table


def _relocate_hot_replicas(table: RoutingTable, alive: set,
                           load: Dict[str, int], node_info, awareness,
                           watermark_low: float, hot: set,
                           indices_meta: Dict) -> None:
    """Move replicas off high-watermark nodes when (and only when) a
    target under the low watermark exists. A STARTED (data-bearing) source
    stays until its replacement has started — relocation keeps both copies
    live like the reference's RELOCATING state; only empty INITIALIZING
    copies move directly."""
    for index, shards in table.items():
        desired_replicas = indices_meta[index].num_replicas
        for copies in shards.values():
            # phase 1: a replacement started — retire the hot source
            healthy_started = [c for c in copies
                               if not c.primary
                               and c.state == ShardRoutingState.STARTED
                               and c.node_id not in hot]
            for c in list(copies):
                if (not c.primary and c.node_id in hot
                        and len(healthy_started) >= desired_replicas):
                    copies.remove(c)
                    load[c.node_id] = load.get(c.node_id, 1) - 1
            # phase 2: spawn replacements / move empty copies
            for copy in list(copies):
                if copy.primary or copy.node_id not in hot:
                    continue
                used = {c.node_id for c in copies if c is not copy}
                candidates = [n for n in alive if n not in used]
                target = _pick_node(candidates, load,
                                    [c for c in copies if c is not copy],
                                    node_info, awareness, watermark_low)
                if target is None or target == copy.node_id:
                    continue
                if copy.state == ShardRoutingState.INITIALIZING:
                    # empty copy: move it outright
                    load[copy.node_id] = load.get(copy.node_id, 1) - 1
                    load[target] = load.get(target, 0) + 1
                    copy.node_id = target
                else:
                    # data-bearing copy: add the target alongside; the
                    # source retires on a later reroute once it starts
                    copies.append(ShardRouting(
                        copy.index, copy.shard_id, target, False,
                        ShardRoutingState.INITIALIZING))
                    load[target] = load.get(target, 0) + 1


def _rebalance_replicas(table: RoutingTable, alive: set,
                        load: Dict[str, int],
                        node_info: Optional[Dict[str, dict]] = None,
                        awareness_attributes: Optional[List[str]] = None,
                        watermark_low: float = WATERMARK_LOW) -> None:
    """Move freshly-assigned (INITIALIZING) replicas off overloaded nodes —
    the greedy fill can pile ties onto one node (BalancedShardsAllocator's
    balancing step). Started replicas are never moved here (moving them
    costs a recovery; rebalancing of started copies is a later round)."""
    improved = True
    while improved:
        improved = False
        for shards in table.values():
            for copies in shards.values():
                if any(c.state == ShardRoutingState.RELOCATING
                       for c in copies):
                    continue  # don't shuffle an explicit move's target
                for copy in copies:
                    if copy.primary or copy.state != ShardRoutingState.INITIALIZING:
                        continue
                    used = {c.node_id for c in copies if c is not copy}
                    candidates = [n for n in alive if n not in used]
                    best = _pick_node(candidates, load,
                                      [c for c in copies if c is not copy],
                                      node_info, awareness_attributes,
                                      watermark_low)
                    if best is not None and copy.node_id is not None and \
                            load.get(best, 0) + 1 < load.get(copy.node_id, 0):
                        load[copy.node_id] -= 1
                        load[best] = load.get(best, 0) + 1
                        copy.node_id = best
                        improved = True


# ---------------------------------------------------------------------------
# Reroute commands (cluster/routing/allocation/command/*.java)
# ---------------------------------------------------------------------------


class RerouteException(Exception):
    """A reroute command failed validation (illegal_argument shape)."""


def _find_copies(table: RoutingTable, index: str, shard: int,
                 cmd: str) -> List[ShardRouting]:
    if index not in table:
        raise RerouteException(f"[{cmd}] no such index [{index}]")
    if shard not in table[index]:
        raise RerouteException(f"[{cmd}] no such shard [{index}][{shard}]")
    return table[index][shard]


def apply_command(table: RoutingTable, indices_meta: Dict,
                  node_ids: Dict[str, str], name: str, args: dict) -> dict:
    """Apply ONE reroute command in place; returns its explanation entry.

    node_ids: {accepted name or id -> node_id} for node resolution.
    Commands (AllocationCommands.registerFactory set, 6.x):
    move, cancel, allocate_replica, allocate_empty_primary,
    allocate_stale_primary.
    """
    def node_of(key: str, value) -> str:
        nid = node_ids.get(str(value))
        if nid is None:
            raise RerouteException(
                f"[{name}] no node found for [{key}] = [{value}]")
        return nid

    index = str(args.get("index", ""))
    shard = int(args.get("shard", -1))
    copies = _find_copies(table, index, shard, name)
    decisions = []
    if name == "move":
        src = node_of("from_node", args.get("from_node"))
        dst = node_of("to_node", args.get("to_node"))
        copy = next((c for c in copies if c.node_id == src), None)
        if copy is None:
            raise RerouteException(
                f"[move] shard [{index}][{shard}] not found on node [{src}]")
        if copy.state != ShardRoutingState.STARTED:
            raise RerouteException(
                f"[move] shard [{index}][{shard}] on node [{src}] is "
                f"[{copy.state}]; only STARTED shards can be moved")
        if any(c.node_id == dst for c in copies):
            raise RerouteException(
                f"[move] a copy of [{index}][{shard}] already exists on "
                f"node [{dst}] (SameShardAllocationDecider)")
        # RELOCATING source + INITIALIZING target, like the reference;
        # a later reroute retires the source once ITS target starts (the
        # explicit relocating_to link — matching any started same-role
        # peer would drop a healthy source while the target still
        # recovers). The target inherits the source's primary flag
        # (MoveAllocationCommand relocates the primary AS a primary —
        # otherwise retiring the source would leave no primary copy)
        copy.state = ShardRoutingState.RELOCATING
        copy.relocating_to = dst
        copies.append(ShardRouting(index, shard, dst, copy.primary,
                                   ShardRoutingState.INITIALIZING))
        decisions.append({"decider": "same_shard", "decision": "YES",
                          "explanation": f"moving to [{dst}]"})
    elif name == "cancel":
        nid = node_of("node", args.get("node"))
        copy = next((c for c in copies if c.node_id == nid), None)
        if copy is None:
            raise RerouteException(
                f"[cancel] shard [{index}][{shard}] not found on node "
                f"[{nid}]")
        if copy.primary and not args.get("allow_primary", False):
            raise RerouteException(
                f"[cancel] can't cancel [{index}][{shard}] on node "
                f"[{nid}], shard is primary and allow_primary is false")
        copies.remove(copy)
        decisions.append({"decider": "cancel", "decision": "YES",
                          "explanation": f"cancelled on [{nid}]"})
    elif name == "allocate_replica":
        nid = node_of("node", args.get("node"))
        if not any(c.primary for c in copies):
            raise RerouteException(
                f"[allocate_replica] trying to allocate a replica shard "
                f"[{index}][{shard}], while corresponding primary shard "
                f"is still unassigned")
        if any(c.node_id == nid for c in copies):
            raise RerouteException(
                f"[allocate_replica] a copy of [{index}][{shard}] already "
                f"exists on node [{nid}]")
        md = indices_meta.get(index)
        assigned_replicas = sum(1 for c in copies if not c.primary)
        if md is not None and assigned_replicas >= md.num_replicas:
            raise RerouteException(
                f"[allocate_replica] all replica copies of "
                f"[{index}][{shard}] are already assigned")
        copies.append(ShardRouting(index, shard, nid, False,
                                   ShardRoutingState.INITIALIZING))
        decisions.append({"decider": "replica_after_primary",
                          "decision": "YES",
                          "explanation": f"allocated replica on [{nid}]"})
    elif name in ("allocate_empty_primary", "allocate_stale_primary"):
        nid = node_of("node", args.get("node"))
        if not args.get("accept_data_loss", False):
            raise RerouteException(
                f"[{name}] allocating an empty primary for "
                f"[{index}][{shard}] can result in data loss; please "
                f"confirm by setting the accept_data_loss parameter to "
                f"true")
        live = next((c for c in copies
                     if c.primary and c.state == ShardRoutingState.STARTED),
                    None)
        if live is not None:
            raise RerouteException(
                f"[{name}] primary [{index}][{shard}] is already assigned")
        # drop any retained dead-primary routing and start over on nid
        for c in list(copies):
            if c.primary:
                copies.remove(c)
        copies.insert(0, ShardRouting(index, shard, nid, True,
                                      ShardRoutingState.INITIALIZING))
        decisions.append({"decider": "force_primary", "decision": "YES",
                          "explanation": f"forced primary on [{nid}] "
                                         f"(accept_data_loss)"})
    else:
        raise RerouteException(f"unknown reroute command [{name}]")
    return {"command": name, "parameters": dict(args),
            "decisions": decisions}


def routing_to_dict(table: RoutingTable) -> dict:
    return {
        name: {
            str(sid): [c.to_dict() for c in copies]
            for sid, copies in shards.items()
        }
        for name, shards in table.items()
    }


def routing_from_dict(d: dict) -> RoutingTable:
    out: RoutingTable = {}
    for name, shards in d.items():
        out[name] = {}
        for sid, copies in shards.items():
            out[name][int(sid)] = [
                ShardRouting(c["index"], c["shard"], c["node"], c["primary"],
                             c["state"],
                             relocating_to=c.get("relocating_node"))
                for c in copies
            ]
    return out
