"""Multi-node cluster: membership, state publish, replication, recovery.

Role models (SURVEY §3.3–3.5, §5.3):
- membership/publish: ``ZenDiscovery`` + ``PublishClusterStateAction`` —
  simplified to single-master-by-lowest-id (ElectMasterService's sort) with
  direct state publish (the two-phase commit degenerates in-process;
  quorum arrives with real DCN in a later round, per SURVEY §7.3 "start
  single-master, defer election").
- writes: ``TransportReplicationAction``/``ReplicationOperation`` — primary
  assigns seqno, forwards to in-sync replicas, failing replicas are
  reported to the master (fail-shard) and dropped from the routing table.
- recovery: ``RecoverySourceHandler`` — ops-based: the primary streams its
  live docs as seqno-stamped ops (phase2 replay); the replica indexes them
  and is marked STARTED.
- failover: master detects a departed node (transport failure / explicit
  leave), reroutes: surviving replica promoted to primary with a bumped
  primary term.

Each ClusterNode hosts only the shards routed to it. A coordinator-side
search fans out per shard copy and merges — hits are fully materialized at
the shard (query+fetch combined; the reference's two-phase fetch is an
optimization this path adds later).
"""

from __future__ import annotations

import base64
import hashlib
import os
import shutil
import threading
import time
from typing import Dict, List, Optional, Tuple

from elasticsearch_tpu.analysis.analyzers import AnalysisRegistry
from elasticsearch_tpu.cluster.allocation import (
    RoutingTable,
    allocate,
    routing_from_dict,
    routing_to_dict,
)
from elasticsearch_tpu.cluster.state import (
    IndexMetadata,
    ShardRouting,
    ShardRoutingState,
)
from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuException,
    IllegalArgumentException,
    IndexNotFoundException,
    NodeNotConnectedException,
)
from elasticsearch_tpu.common import settings as S
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.common.integrity import integrity_service
from elasticsearch_tpu.index.shard import IndexShard
from elasticsearch_tpu.index.store import CorruptIndexException
from elasticsearch_tpu.mapper.mapping import MapperService
from elasticsearch_tpu.transport.local import (
    ConnectionHealth,
    RetryPolicy,
    TransportHub,
    TransportService,
)
from elasticsearch_tpu.utils.murmur3 import shard_id_for

ACTION_PUBLISH = "internal:cluster/coordination/publish_state"
ACTION_COMMIT = "internal:cluster/coordination/commit_state"
ACTION_JOIN = "internal:discovery/zen/join"
ACTION_LEAVE = "internal:discovery/zen/leave"
ACTION_SHARD_FAILED = "internal:cluster/shard/failure"
ACTION_SHARD_STARTED = "internal:cluster/shard/started"
ACTION_WRITE_PRIMARY = "indices:data/write/bulk[s][p]"
ACTION_WRITE_REPLICA = "indices:data/write/bulk[s][r]"
ACTION_GET = "indices:data/read/get[s]"
ACTION_QUERY = "indices:data/read/search[phase/query+fetch]"
ACTION_REFRESH = "indices:admin/refresh[s]"
ACTION_RECOVER = "internal:index/shard/recovery/start_recovery"
ACTION_RECOVERY_FINALIZE = "internal:index/shard/recovery/finalize"
ACTION_RECOVER_FILES_START = "internal:index/shard/recovery/files/start"
ACTION_RECOVER_FILE_CHUNK = "internal:index/shard/recovery/files/chunk"
ACTION_RECOVER_FILES_CLOSE = "internal:index/shard/recovery/files/close"
ACTION_MASTER_PING = "internal:discovery/zen/fd/master_ping"

# phase1 file-chunk size (RecoverySettings.CHUNK_SIZE analog, 512KB)
RECOVERY_CHUNK_BYTES = 512 * 1024
# a source-side file session whose target went silent for this long is
# reclaimed (the reference cancels recoveries on timeout); sessions hold a
# full in-memory snapshot of the shard's files
RECOVERY_SESSION_MAX_AGE_S = 600.0

# ---------------------------------------------------------------------------
# Recovery progress registry (_cat/recovery — ISSUE 10 satellite).
#
# Target-side ClusterNodes record each peer recovery's live progress here
# (stage init -> index -> translog -> finalize -> done, file/bytes/ops
# counts, source -> target), keyed per copy; the REST layer renders the
# rows like the reference's RecoveryState exposed through
# RestCatRecoveryAction. Process-global (like the transport stats
# registry in transport/local.py) so in-one-process clusters and the
# single-node REST surface share one view; bounded by eviction of done
# rows beyond a cap.
# ---------------------------------------------------------------------------

_RECOVERY_PROGRESS: Dict[Tuple[str, int, str], dict] = {}
_RECOVERY_PROGRESS_LOCK = threading.Lock()
# total-row cap: finished rows retire first, then the OLDEST stale
# in-flight ones (a recovery that died mid-pull never reaches "done" —
# without aging those out the registry would grow per churned copy)
_RECOVERY_PROGRESS_MAX_ROWS = 128


def record_recovery_progress(index: str, shard: int, target: str,
                             **updates) -> None:
    """Create/update one copy's recovery-progress row; counters passed
    as ``add_<field>=n`` increment, plain fields assign."""
    key = (index, int(shard), target)
    with _RECOVERY_PROGRESS_LOCK:
        row = _RECOVERY_PROGRESS.get(key)
        if row is None:
            row = _RECOVERY_PROGRESS[key] = {
                "index": index, "shard": int(shard), "target": target,
                "source": None, "type": "peer", "stage": "init",
                "files_total": 0, "files_recovered": 0,
                "bytes_total": 0, "bytes_recovered": 0,
                "ops_total": 0, "ops_recovered": 0,
                "start_ms": int(time.time() * 1000), "stop_ms": None,
            }
            # bounded registry: evict finished rows first (oldest
            # stop_ms), then the oldest stale in-flight rows
            excess = len(_RECOVERY_PROGRESS) - _RECOVERY_PROGRESS_MAX_ROWS
            if excess > 0:
                victims = sorted(
                    (k for k in _RECOVERY_PROGRESS if k != key),
                    key=lambda k: (
                        _RECOVERY_PROGRESS[k]["stage"] != "done",
                        _RECOVERY_PROGRESS[k]["stop_ms"]
                        or _RECOVERY_PROGRESS[k]["start_ms"] or 0))
                for k in victims[:excess]:
                    _RECOVERY_PROGRESS.pop(k, None)
        for field, value in updates.items():
            if field.startswith("add_"):
                row[field[4:]] = row.get(field[4:], 0) + value
            else:
                row[field] = value


def recovery_progress_rows() -> List[dict]:
    """Snapshot of every tracked recovery, in-flight first then by
    recency — the _cat/recovery row source."""
    with _RECOVERY_PROGRESS_LOCK:
        rows = [dict(r) for r in _RECOVERY_PROGRESS.values()]
    rows.sort(key=lambda r: (r["stage"] == "done", -(r["start_ms"] or 0)))
    return rows


def clear_recovery_progress() -> None:
    with _RECOVERY_PROGRESS_LOCK:
        _RECOVERY_PROGRESS.clear()


def _time_setting(setting, settings: Settings) -> float:
    """Resolve a time Setting to seconds — Setting.get returns string
    defaults ('50ms') unparsed."""
    from elasticsearch_tpu.common.units import parse_time_value

    v = setting.get(settings)
    return parse_time_value(v, setting.key) if isinstance(v, str) else float(v)


class NotMasterException(ElasticsearchTpuException):
    """A master-only operation raced with a mastership change; callers
    on the RPC path translate this to a benign {'ok': False}."""


class FailedToCommitClusterStateException(ElasticsearchTpuException):
    """The publish quorum was not reached; the master stepped down and
    the state change is NOT committed (discovery/zen/publish —
    FailedToCommitClusterStateException). Clients must treat the request
    as failed."""

    status_code = 503


class ClusterNode:
    """One node of the in-process cluster (a real Node analog hosting only
    its allocated shards)."""

    def __init__(self, name: str, hub: TransportHub, master_eligible: bool = True,
                 data: bool = True, attrs: Optional[Dict[str, str]] = None,
                 awareness_attributes: Optional[List[str]] = None,
                 min_master_nodes: int = 1,
                 settings: Optional[Settings] = None,
                 data_path: Optional[str] = None):
        self.name = name
        self.node_id = name  # stable, human-readable ids make tests clear
        self.master_eligible = master_eligible
        self.data = data
        # durable shard storage (translog + store under
        # <data_path>/<index>/<shard>): a SIGKILLed node restarted over
        # the same path replays acked writes from the translog
        # (crash-recovery contract; None = in-memory shards, the
        # historical test default)
        self.data_path = data_path
        # transport resilience knobs (common/settings.py registry): per-
        # attempt request deadlines, the RetryableAction-style backoff
        # policies, and the per-node connection health tracker
        self.settings = settings or Settings.EMPTY
        s = self.settings
        self.request_timeout = _time_setting(S.TRANSPORT_REQUEST_TIMEOUT, s)
        self.fd_ping_timeout = _time_setting(S.FD_PING_TIMEOUT, s)
        self.publish_timeout = _time_setting(S.PUBLISH_TIMEOUT, s)
        self.replication_timeout = _time_setting(S.REPLICATION_TIMEOUT, s)
        self.recovery_action_timeout = _time_setting(S.RECOVERY_ACTION_TIMEOUT, s)
        self.retry_policy = RetryPolicy(
            max_attempts=S.TRANSPORT_RETRY_MAX_ATTEMPTS.get(s),
            initial_backoff=_time_setting(S.TRANSPORT_RETRY_INITIAL_BACKOFF, s),
            backoff_multiplier=S.TRANSPORT_RETRY_BACKOFF_MULTIPLIER.get(s),
            max_backoff=_time_setting(S.TRANSPORT_RETRY_MAX_BACKOFF, s))
        self.fd_retry = self.retry_policy.derive(
            max_attempts=S.FD_PING_RETRIES.get(s))
        self.recovery_retry = RetryPolicy(
            max_attempts=S.RECOVERY_MAX_RETRIES.get(s),
            initial_backoff=_time_setting(S.RECOVERY_RETRY_DELAY_NETWORK, s),
            backoff_multiplier=S.TRANSPORT_RETRY_BACKOFF_MULTIPLIER.get(s),
            max_backoff=_time_setting(S.TRANSPORT_RETRY_MAX_BACKOFF, s))
        # publish/replication retries are bounded by an OVERALL deadline:
        # an unresponsive peer costs one timeout, not timeout x attempts
        # (drops fail fast and still get their backoff retries)
        self.publish_retry = self.retry_policy.derive(
            overall_timeout=self.publish_timeout)
        self.replication_retry = self.retry_policy.derive(
            overall_timeout=self.replication_timeout)
        # fail-shard reports guard against SILENT divergence (an
        # unreported failed replica stays STARTED in the routing table
        # and could be promoted later, losing acked writes) — they get
        # twice the retry budget of a normal request
        self.report_retry = self.retry_policy.derive(
            max_attempts=2 * self.retry_policy.max_attempts)
        # node attributes (node.attr.* — awareness zones etc.) + simulated
        # disk usage fraction (ClusterInfoService/FsProbe analog; tests set
        # it and call reroute)
        self.attrs = dict(attrs or {})
        self.disk_used_fraction = 0.0
        # master-side: configured awareness attributes
        # (cluster.routing.allocation.awareness.attributes)
        self.awareness_attributes = list(awareness_attributes or [])
        # master-side: per-node info collected from joins
        self.node_info_map: Dict[str, dict] = {}
        self.transport = TransportService(
            self.node_id, hub,
            health=ConnectionHealth(
                failure_threshold=S.TRANSPORT_HEALTH_FAILURE_THRESHOLD.get(s),
                quarantine_s=_time_setting(S.TRANSPORT_HEALTH_QUARANTINE, s)))
        self.hub = hub
        # cluster-state copy (every node holds the latest published state).
        # (epoch, version) orders states like the reference's cluster-state
        # term+version: the epoch bumps at every election, so a deposed
        # master's re-published state (same version base, old epoch) is
        # rejected by every node that followed the new master
        self.cluster_epoch = 0
        self.state_version = 0
        self.indices_meta: Dict[str, IndexMetadata] = {}
        # per-shard primary terms, owned by the master and carried in the
        # published state (reference: IndexMetaData.primaryTerm(shardId),
        # bumped on every primary promotion/reassignment) — replicas learn
        # the current term from the publish, not from write traffic
        self.primary_terms: Dict[Tuple[str, int], int] = {}
        self.routing: RoutingTable = {}
        self.known_nodes: List[str] = []
        self.master_id: Optional[str] = None
        # discovery.zen.minimum_master_nodes: the election AND the publish
        # commit both require this many master-eligible nodes (self
        # included) — the split-brain guard (ElectMasterService
        # .hasEnoughMasterNodes / PublishClusterStateAction commit quorum).
        # The reference's default is 1 (unsafe by default, warned about);
        # production clusters set (eligible // 2) + 1.
        self.min_master_nodes = max(1, int(min_master_nodes))
        # two-phase publish: follower-side buffered state awaiting commit
        # keyed by (epoch, version) — dropped when superseded
        self._pending_publish: Optional[dict] = None
        # while a master-side state update is uncommitted, shards it
        # removes are parked here instead of closed (rollback support)
        self._removed_shards: Optional[list] = None
        # local shards: (index, shard_id) -> IndexShard
        self.shards: Dict[Tuple[str, int], IndexShard] = {}
        self.mappers: Dict[str, MapperService] = {}
        self._lock = threading.RLock()
        # serializes primary writes vs recovery finalize ONLY — a separate
        # lock so it never participates in the node-lock ordering of
        # publish/apply-state paths (cross-node deadlock avoidance: while
        # held, the only outbound calls are lock-free replica writes)
        self._replication_lock = threading.RLock()
        # phase1 file-recovery sessions on this node as a recovery SOURCE:
        # session id -> {"files": {relpath: bytes}, "t0", "sent"}. The file
        # bytes are snapshotted at session start (the reference holds an
        # IndexCommit ref instead) so concurrent flush/merge can't mutate
        # the view mid-transfer.
        self._recovery_sessions: Dict[str, dict] = {}
        self._recovery_session_seq = 0
        # indices.recovery.max_bytes_per_sec analog (None = unthrottled)
        self.recovery_max_bytes_per_sec: Optional[float] = None
        # master-side registry of last-copy corruption (ISSUE 16):
        # (index, sid) -> {"node", "reason"} — the copy stays routed
        # (dropping it would let the allocator fill a fresh EMPTY
        # primary, i.e. silent data loss) and the quarantine is surfaced
        # through allocation explain / _cat/shards instead
        self.corrupt_retained: Dict[Tuple[str, int], dict] = {}
        self._register_handlers()

    # ------------------------------------------------------------------

    def _register_handlers(self) -> None:
        t = self.transport
        t.register_handler(ACTION_PUBLISH, self._on_publish)
        t.register_handler(ACTION_COMMIT, self._on_commit)
        t.register_handler(ACTION_JOIN, self._on_join)
        t.register_handler(ACTION_LEAVE, self._on_leave)
        t.register_handler(ACTION_SHARD_FAILED, self._on_shard_failed)
        t.register_handler(ACTION_SHARD_STARTED, self._on_shard_started)
        t.register_handler(ACTION_WRITE_PRIMARY, self._on_write_primary)
        t.register_handler(ACTION_WRITE_REPLICA, self._on_write_replica)
        t.register_handler(ACTION_GET, self._on_get)
        t.register_handler(ACTION_QUERY, self._on_query)
        t.register_handler(ACTION_REFRESH, self._on_refresh)
        t.register_handler(ACTION_RECOVER, self._on_start_recovery)
        t.register_handler(ACTION_RECOVERY_FINALIZE, self._on_recovery_finalize)
        t.register_handler(ACTION_RECOVER_FILES_START,
                           self._on_start_file_recovery)
        t.register_handler(ACTION_RECOVER_FILE_CHUNK,
                           self._on_recovery_file_chunk)
        t.register_handler(ACTION_RECOVER_FILES_CLOSE,
                           self._on_recovery_files_close)
        t.register_handler(ACTION_MASTER_PING, self._on_master_ping)

    @property
    def is_master(self) -> bool:
        return self.master_id == self.node_id

    # ------------------------------------------------------------------
    # Master-side: membership + state updates
    # ------------------------------------------------------------------

    def bootstrap_cluster(self) -> None:
        """First node: elect self."""
        with self._lock:
            self.master_id = self.node_id
            self.known_nodes = [self.node_id]
            self.node_info_map[self.node_id] = {
                "attrs": self.attrs, "disk": self.disk_used_fraction,
                "master_eligible": self.master_eligible}
            self.cluster_epoch = 1
            self.state_version = 1

    def join(self, seed_node: str) -> None:
        """Join via any known node (UnicastZenPing seed analog)."""
        payload = {
            "node": self.node_id,
            "master_eligible": self.master_eligible,
            "data": self.data,
            "attrs": self.attrs,
            "disk": self.disk_used_fraction,
        }
        resp = self.transport.send_request(
            seed_node, ACTION_JOIN, payload,
            timeout=self.request_timeout, retry=self.retry_policy)
        if resp.get("master") != seed_node:
            # redirected to the actual master
            self.transport.send_request(
                resp["master"], ACTION_JOIN, payload,
                timeout=self.request_timeout, retry=self.retry_policy)

    def _on_join(self, payload, src) -> dict:
        with self._lock:
            if not self.is_master:
                return {"master": self.master_id}
            node = payload["node"]
            if node not in self.known_nodes:
                self.known_nodes.append(node)
            self.node_info_map[node] = {
                "attrs": payload.get("attrs") or {},
                "disk": payload.get("disk") or 0.0,
                "master_eligible": bool(payload.get("master_eligible", True)),
            }
        self._master_reroute_and_publish()
        return {"master": self.node_id}

    def node_left(self, departed: str) -> None:
        """Master-side removal (fault detection outcome or explicit leave)."""
        with self._lock:
            if not self.is_master:
                raise IllegalArgumentException("node_left must run on the master")
            if departed in self.known_nodes:
                self.known_nodes.remove(departed)
            self.node_info_map.pop(departed, None)
        self._master_reroute_and_publish()

    def _on_leave(self, payload, src) -> dict:
        """Graceful-leave announcement (ISSUE 14, docs/RESILIENCE.md
        "Rollout & drain"): the departing node tells the master BEFORE
        shutting down, so the coordinator routes around it and replicas
        promote NOW instead of after the fault-detection timeout."""
        with self._lock:
            if not self.is_master:
                return {"ok": False, "master": self.master_id}
        try:
            self.node_left(payload["node"])
        except (IllegalArgumentException,
                FailedToCommitClusterStateException):
            return {"ok": False, "master": self.master_id}
        return {"ok": True}

    def graceful_leave(self, timeout_s: float = 2.0) -> bool:
        """Announce this node's departure before shutdown (the rollout
        contract): a follower notifies the master (one redirect hop,
        like join); the master ABDICATES — one state update removes it
        from the node set, hands mastership to the lowest-id other
        eligible node, and reroutes, so its primaries' replicas promote
        under the leave publish instead of after FD timeout. Bounded
        and best-effort: False means peers will learn via fault
        detection, exactly the pre-ISSUE-14 behavior."""
        with self._lock:
            peers = [n for n in self.known_nodes if n != self.node_id]
            master = self.master_id
            am_master = self.is_master
        if not peers:
            return True  # last node: nobody to tell
        if not am_master:
            target = master
            for _hop in range(2):  # one redirect, like join()
                if target is None or target == self.node_id:
                    return False
                try:
                    resp = self.transport.send_request(
                        target, ACTION_LEAVE, {"node": self.node_id},
                        timeout=min(self.request_timeout, timeout_s)) or {}
                except (NodeNotConnectedException,
                        ElasticsearchTpuException):
                    return False
                if resp.get("ok"):
                    return True
                target = resp.get("master")
            return False

        # master: abdicate
        def mutate():
            successor = next(
                (n for n in self._master_eligible_nodes(
                    exclude=self.node_id) if n != self.node_id), None)
            if self.node_id in self.known_nodes:
                self.known_nodes.remove(self.node_id)
            self.node_info_map.pop(self.node_id, None)
            self.master_id = successor
            # a mastership TRANSFER bumps the epoch exactly like an
            # election: followers order states by (epoch, version) and
            # break same-epoch master conflicts toward the LOWER id —
            # without the bump, handing off to a higher-id successor
            # would be rejected as a lost election
            self.cluster_epoch += 1

        try:
            self._submit_state_update(mutate)
            return True
        except (FailedToCommitClusterStateException,
                NodeNotConnectedException, ElasticsearchTpuException):
            return False

    def check_nodes(self) -> List[str]:
        """Fault detection (NodesFaultDetection): master pings all nodes;
        unreachable ones are removed. A ping answered with a HIGHER
        cluster epoch means this node was deposed while partitioned — it
        steps down and rejoins the real cluster (the reference's
        "another master for the cluster" rejoin). Returns departed ids."""
        departed = []
        lagging = []
        new_cluster: Optional[dict] = None
        with self._lock:
            if not self.is_master:
                return []
            peers = [n for n in self.known_nodes if n != self.node_id]
            my_epoch = self.cluster_epoch
            my_version = self.state_version
        # ping OUTSIDE the lock: a slow peer must not stall every other
        # master operation for a socket timeout per FD tick. The ping
        # timeout bounds each attempt so an UNRESPONSIVE (not merely
        # disconnected) node is detected; ping_retries keeps a lossy link
        # from evicting a live node
        for node in peers:
            try:
                resp = self.transport.send_request(
                    node, ACTION_PUBLISH, None,
                    timeout=self.fd_ping_timeout, retry=self.fd_retry)
                resp = resp or {}
                if (resp.get("epoch", 0) > my_epoch
                        or (resp.get("epoch", 0) == my_epoch
                            and (resp.get("master") or self.node_id)
                            < self.node_id)):
                    # a cluster with precedence over ours (higher epoch,
                    # or same epoch under a lower-id master) exists
                    new_cluster = resp
                    break
                if ((resp.get("epoch", my_epoch), resp.get("version",
                                                           my_version))
                        < (my_epoch, my_version)):
                    # the follower missed a publish (drops exhausted the
                    # phase-1 retries): without repair its state DIVERGES
                    # silently until the next unrelated state change —
                    # re-publish the full state to it below
                    lagging.append(node)
            except NodeNotConnectedException:
                departed.append(node)
        if lagging:
            self._republish_to_lagging(lagging, my_epoch, my_version)
        if new_cluster is not None:
            with self._lock:
                self.master_id = new_cluster["master"]
            try:
                self.join(new_cluster["master"])
            except NodeNotConnectedException:
                pass
            return []
        with self._lock:
            still_master = self.master_id == self.node_id
        if still_master:
            remaining = [n for n in peers if n not in departed]
            if self._reachable_eligible(remaining) < self.min_master_nodes:
                # the master lost its quorum (minority side of a
                # partition): step down instead of continuing to accept
                # writes that the majority side will fence
                with self._lock:
                    if self.master_id == self.node_id:
                        self.master_id = None
                return departed
        for node in departed:
            self.node_left(node)
        return departed

    def _republish_to_lagging(self, nodes: List[str], my_epoch: int,
                              my_version: int) -> None:
        """FD repair path: push the CURRENT full state (publish + commit)
        to followers whose ping showed an older (epoch, version). The
        state dict is self-contained, so one round catches a follower up
        no matter how many publishes it missed."""
        with self._lock:
            if not self.is_master:
                return
            if (self.cluster_epoch, self.state_version) < (my_epoch,
                                                           my_version):
                return  # our own view moved backwards (deposed): bail
            state = self._state_dict()
        key = {"epoch": state["epoch"], "version": state["version"]}
        for node in nodes:
            try:
                resp = self.transport.send_request(
                    node, ACTION_PUBLISH, state,
                    timeout=self.publish_timeout,
                    retry=self.publish_retry) or {}
                if resp.get("ok"):
                    self.transport.send_request(
                        node, ACTION_COMMIT, key,
                        timeout=self.publish_timeout,
                        retry=self.publish_retry)
            except (NodeNotConnectedException, ElasticsearchTpuException):
                pass  # still unreachable: the next FD tick retries

    # ------------------------------------------------------------------
    # Master fault detection + re-election (MasterFaultDetection.java:56,
    # ZenDiscovery.handleMasterGone -> ElectMasterService: nodes ping the
    # master; on loss the lowest-id master-eligible survivor elects
    # itself, bumps the state version and republishes; promotions bump
    # primary terms, fencing in-flight writes from the deposed master)
    # ------------------------------------------------------------------

    def _on_master_ping(self, payload, src) -> dict:
        return {"master": self.master_id, "is_master": self.is_master,
                "version": self.state_version,
                "epoch": self.cluster_epoch}

    def _master_eligible_nodes(self, exclude: Optional[str] = None):
        out = []
        for n in self.known_nodes:
            if n == exclude:
                continue
            info = self.node_info_map.get(n) or {}
            eligible = info.get("master_eligible", True)
            if n == self.node_id:
                eligible = self.master_eligible
            if eligible:
                out.append(n)
        return sorted(out)

    def check_master(self) -> Optional[str]:
        """Non-master fault detection: ping the master; on loss run the
        election. Returns the new master id if one was chosen, else None."""
        with self._lock:
            master = self.master_id
            if master == self.node_id:
                return None
        if master is None:
            # headless (stepped down after quorum loss): probe known
            # peers for a live master to rejoin, else run an election —
            # without this the node stays orphaned after the partition
            # heals (the majority removed us; nobody publishes to us)
            for peer in sorted(self.known_nodes):
                if peer == self.node_id:
                    continue
                try:
                    resp = self.transport.send_request(
                        peer, ACTION_MASTER_PING, None,
                        timeout=self.fd_ping_timeout,
                        retry=self.fd_retry) or {}
                except NodeNotConnectedException:
                    continue
                claimed = resp.get("master") if not resp.get("is_master") \
                    else peer
                if claimed and resp.get("epoch", 0) >= self.cluster_epoch:
                    try:
                        self.join(claimed)
                        return claimed
                    except NodeNotConnectedException:
                        continue
            return self._handle_master_failure(None)
        try:
            resp = self.transport.send_request(
                master, ACTION_MASTER_PING, None,
                timeout=self.fd_ping_timeout, retry=self.fd_retry)
            if resp.get("is_master"):
                return None
            # it abdicated/lost an election itself: adopt its view only
            # after VERIFYING the proposed master is alive and actually
            # master — blindly adopting could flip us back to a dead node
            proposed = resp.get("master")
            if proposed and proposed != master:
                try:
                    r2 = self.transport.send_request(
                        proposed, ACTION_MASTER_PING, None,
                        timeout=self.fd_ping_timeout, retry=self.fd_retry)
                    if r2.get("is_master"):
                        with self._lock:
                            self.master_id = proposed
                        return proposed
                except NodeNotConnectedException:
                    pass
            # our presumptive master is alive but not (yet) master: stay
            # put; its own election tick converges the cluster
            return None
        except NodeNotConnectedException:
            pass
        return self._handle_master_failure(master)

    def _handle_master_failure(self, dead: str) -> Optional[str]:
        with self._lock:
            if self.master_id != dead:
                return self.master_id  # someone already converged us
            candidates = self._master_eligible_nodes(exclude=dead)
        # walk candidates in election order, skipping unreachable ones
        # (a previously-dead node may still linger in known_nodes: it must
        # not be "elected" just because its id sorts first); count the
        # reachable eligibles for the quorum check
        reachable = []
        winner = None
        for cand in candidates:
            if cand == self.node_id:
                reachable.append(cand)
                if winner is None:
                    winner = cand
                continue
            try:
                self.transport.send_request(
                    cand, ACTION_MASTER_PING, None,
                    timeout=self.fd_ping_timeout, retry=self.fd_retry)
                reachable.append(cand)
                if winner is None:
                    winner = cand
            except NodeNotConnectedException:
                continue
        if winner is None:
            return None
        if len(reachable) < self.min_master_nodes:
            # not enough master nodes (ElectMasterService
            # .hasEnoughMasterNodes): refuse the election — a minority
            # partition must stay headless rather than split-brain
            return None
        new_master = winner
        if new_master != self.node_id:
            # not the winner: adopt the deterministic result; the winner
            # converges through its own master fault detection tick and
            # publishes the new state to us
            with self._lock:
                if self.master_id == dead:
                    self.master_id = new_master
            return new_master
        with self._lock:
            if self.master_id != dead:
                return self.master_id  # lost a race with another publish
            # assume mastership: bump the epoch (fences the deposed
            # master's future publishes), drop it, reroute (promotes
            # its primaries with bumped terms), republish
            self.master_id = self.node_id
            self.cluster_epoch += 1
            if dead in self.known_nodes:
                self.known_nodes.remove(dead)
            self.node_info_map.pop(dead, None)
            self.node_info_map.setdefault(self.node_id, {
                "attrs": self.attrs, "disk": self.disk_used_fraction,
                "master_eligible": self.master_eligible})
        self._master_reroute_and_publish()
        return self.node_id

    def start_fault_detection(self, interval: float = 1.0) -> None:
        """Background FD ticker: the master pings all nodes
        (NodesFaultDetection), everyone else pings the master
        (MasterFaultDetection)."""
        if getattr(self, "_fd_thread", None):
            return
        self._fd_stop = threading.Event()

        def tick():
            while not self._fd_stop.wait(interval):
                try:
                    if self.is_master:
                        self.check_nodes()
                    else:
                        self.check_master()
                except Exception:  # noqa: BLE001 — FD must never die
                    pass

        self._fd_thread = threading.Thread(target=tick, daemon=True)
        self._fd_thread.start()

    def create_index(self, name: str, settings: Optional[dict] = None,
                     mappings: Optional[dict] = None) -> dict:
        def mutate():
            if not self.is_master:
                raise IllegalArgumentException(
                    "create_index must be sent to the master"
                )
            if name in self.indices_meta:
                from elasticsearch_tpu.common.errors import IndexAlreadyExistsException

                raise IndexAlreadyExistsException(name)
            self.indices_meta[name] = IndexMetadata(
                name,
                Settings.from_dict(settings or {}).with_index_prefix(),
                mappings or {"properties": {}},
                creation_date=int(time.time() * 1000),
            )

        self._submit_state_update(mutate)
        return {"acknowledged": True, "index": name}

    def delete_index(self, name: str) -> dict:
        def mutate():
            if not self.is_master:
                raise IllegalArgumentException("delete_index must run on master")
            if name not in self.indices_meta:
                raise IndexNotFoundException(name)
            del self.indices_meta[name]
            self.routing.pop(name, None)

        self._submit_state_update(mutate)
        return {"acknowledged": True}

    def update_node_disk(self, node_id: str, used_fraction: float) -> None:
        """Master-side disk-usage report (DiskThresholdMonitor input);
        callers follow with a reroute to act on watermark crossings."""
        with self._lock:
            if not self.is_master:
                raise IllegalArgumentException(
                    "update_node_disk must run on the master")
            info = self.node_info_map.setdefault(
                node_id, {"attrs": {}, "disk": 0.0})
            info["disk"] = used_fraction

    def reroute(self) -> None:
        """Explicit reroute (POST /_cluster/reroute analog)."""
        self._master_reroute_and_publish()

    def _master_reroute_and_publish(self) -> None:
        self._submit_state_update(lambda: None)

    def _submit_state_update(self, mutate) -> None:
        """MasterService.runTasks analog: apply `mutate` + reroute +
        self-apply under the lock, then publish to the other nodes
        OUTSIDE it: a follower's publish handler may synchronously
        recover replicas and report shard-started back to this master —
        holding our lock across the publish round-trip would deadlock
        that nested RPC over a real (TCP) transport. (The in-process hub
        hid this: same-thread RLock reentrancy.) Callers must therefore
        NOT hold self._lock when calling this.

        If the commit quorum fails, the pre-change snapshot is restored
        before FailedToCommitClusterStateException propagates: the
        reference master only applies a state after its publish quorum
        acks (PublishClusterStateAction), so a minority master must not
        keep serving a change its client was told did NOT commit. Local
        shards the uncommitted change removed (e.g. a rolled-back
        delete_index) are held open until the commit succeeds and are
        resurrected with their data on rollback — recreating them empty
        would lose the master's copy while claiming nothing happened."""
        with self._lock:
            snapshot = self._state_dict()
            removed: list = []
            self._removed_shards = removed
            try:
                mutate()
                state, deferred = self._master_reroute_locked()
            finally:
                self._removed_shards = None
        try:
            for action in deferred:  # own-primary started reports etc.
                # a deferred action may itself publish (shard-started →
                # nested _submit_state_update) and hit the same failed
                # quorum — that must roll back THIS change too
                action()
            with self._lock:
                # a nested publish may have superseded `state`; shipping
                # the stale version would cost a full 2-phase broadcast
                # every follower then rejects as stale
                superseded = ((self.cluster_epoch, self.state_version)
                              > (state["epoch"], state["version"]))
            if not superseded:
                self._publish_to_followers(state)
        except FailedToCommitClusterStateException:
            with self._lock:
                # put removed shards back BEFORE re-adopting the
                # snapshot so its reconcile finds the data intact
                for key, shard in removed:
                    if key not in self.shards:
                        self.shards[key] = shard
                restore = self._adopt_state_locked(snapshot)
                self.master_id = None  # stay stepped down post-rollback
            for action in restore:
                action()
            raise
        for _key, shard in removed:  # committed: the removal is final
            shard.close()

    def _reachable_eligible(self, nodes) -> int:
        """Count of master-eligible nodes among `nodes` (self included if
        eligible) — the election/commit quorum input."""
        count = 1 if self.master_eligible else 0
        for n in nodes:
            if n == self.node_id:
                continue
            info = self.node_info_map.get(n) or {}
            if info.get("master_eligible", True):
                count += 1
        return count

    def _publish_to_followers(self, state: dict) -> None:
        """Two-phase publish (PublishClusterStateAction): phase 1 sends
        the state, followers BUFFER it; once master-eligible acks (self
        included) reach minimum_master_nodes, phase 2 commits and
        followers apply. Short of the quorum, the master steps down
        (FailedToCommitClusterStateException -> rejoin) and the buffered
        state dies unapplied on every follower."""
        key = {"epoch": state["epoch"], "version": state["version"]}
        acks = 1 if self.master_eligible else 0
        reached = []
        for node in state["nodes"]:
            if node == self.node_id:
                continue
            try:
                # per-follower deadline + retry: a transient drop retries
                # with backoff; an unresponsive follower costs at most the
                # publish timeout and simply does not ack (timeout quorum
                # — PublishClusterStateAction's AckListener deadline)
                resp = self.transport.send_request(
                    node, ACTION_PUBLISH, state,
                    timeout=self.publish_timeout,
                    retry=self.publish_retry) or {}
                if not resp.get("ok"):
                    continue  # explicit rejection (stale epoch) != ack
                reached.append(node)
                info = self.node_info_map.get(node) or {}
                if info.get("master_eligible", True):
                    acks += 1
            except NodeNotConnectedException:
                pass  # fault detection will remove it
        if acks < self.min_master_nodes:
            with self._lock:
                if self.master_id == self.node_id:
                    self.master_id = None  # stepped down; a quorum-backed
                    # master (or a healed partition) re-converges us
            raise FailedToCommitClusterStateException(
                f"publish of cluster state [{state['version']}] reached "
                f"{acks} of the required {self.min_master_nodes} "
                f"master-eligible acks")
        for node in reached:
            try:
                self.transport.send_request(
                    node, ACTION_COMMIT, key,
                    timeout=self.publish_timeout, retry=self.publish_retry)
            except Exception:  # noqa: BLE001 — commit is best-effort
                # past the quorum the state IS committed; a follower
                # whose apply blew up (e.g. its deferred shard-started
                # report hit a nested failed quorum) must not bubble
                # that back here and make us roll back a committed
                # change — it will catch up on the next publish
                pass

    def _master_reroute_locked(self) -> Tuple[dict, list]:
        data_nodes = [n for n in self.known_nodes]  # all nodes are data nodes here
        # prune the corrupt-retained registry: a deleted index releases
        # its keys (a RECREATED index with the same name must get a
        # fresh primary — it never had the lost data)
        self.corrupt_retained = {
            k: v for k, v in self.corrupt_retained.items()
            if k[0] in self.indices_meta}
        old_primaries = {
            (index, sid): copy.node_id
            for index, shards in self.routing.items()
            for sid, copies in shards.items()
            for copy in copies if copy.primary
        }
        self.routing = allocate(
            self.indices_meta, data_nodes, self.routing,
            node_info=self.node_info_map,
            awareness_attributes=self.awareness_attributes or None,
            no_fresh_primary=set(self.corrupt_retained) or None)
        # bump the term wherever the primary copy moved to another node
        # (promotion after failure, cancel+reassign): the old primary may
        # still be alive and issuing writes — the higher term fences it
        for index, shards in self.routing.items():
            for sid, copies in shards.items():
                key = (index, sid)
                self.primary_terms.setdefault(key, 1)
                new_primary = next(
                    (c.node_id for c in copies if c.primary), None)
                old = old_primaries.get(key)
                if (new_primary is not None and old is not None
                        and new_primary != old):
                    self.primary_terms[key] += 1
        self.state_version += 1
        state = self._state_dict()
        deferred = self._apply_state_locked(state)  # self-apply
        return state, deferred

    def _state_dict(self) -> dict:
        return {
            "epoch": self.cluster_epoch,
            "version": self.state_version,
            "master": self.master_id,
            "nodes": list(self.known_nodes),
            "indices": {
                name: {
                    "settings": md.settings.as_dict(),
                    "mappings": md.mappings,
                    "state": md.state,
                    # full IndexMetadata: every apply (follower AND the
                    # master's own self-apply/rollback) rebuilds from
                    # this dict, so omitting a field here silently wipes
                    # it cluster-wide
                    "aliases": md.aliases,
                    "creation_date": md.creation_date,
                    "version": md.version,
                }
                for name, md in self.indices_meta.items()
            },
            "routing": routing_to_dict(self.routing),
            "primary_terms": {
                f"{index}#{sid}": term
                for (index, sid), term in self.primary_terms.items()
            },
            # every node learns eligibility so any survivor can compute
            # the deterministic election result (ElectMasterService sorts
            # master-eligible nodes; lowest id wins)
            "node_info": {
                n: {"master_eligible": bool(
                    info.get("master_eligible", True)),
                    "attrs": info.get("attrs") or {},
                    "disk": info.get("disk") or 0.0}
                for n, info in self.node_info_map.items()
            },
        }

    # ------------------------------------------------------------------
    # Applier side (IndicesClusterStateService.applyClusterState analog)
    # ------------------------------------------------------------------

    def _on_publish(self, payload, src) -> dict:
        if payload is None:
            # ping: answer with our view so a deposed master can notice
            # the higher-epoch cluster and step down, and so the master
            # can spot a LAGGING follower (missed publish under faults)
            # and re-publish to it (check_nodes)
            return {"ok": True, "master": self.master_id,
                    "epoch": self.cluster_epoch,
                    "version": self.state_version}
        with self._lock:
            if payload["epoch"] < self.cluster_epoch:
                # a deposed master re-publishing from a stale epoch: the
                # rejection must be VISIBLE in the ack so its commit
                # quorum fails (not just swallowed at apply time)
                return {"ok": False, "reason": "stale epoch",
                        "epoch": self.cluster_epoch,
                        "master": self.master_id}
            pending = self._pending_publish
            if pending is None or (
                    (payload["epoch"], payload["version"])
                    >= (pending["epoch"], pending["version"])):
                self._pending_publish = payload
        return {"ok": True, "version": payload["version"]}

    def _on_commit(self, payload, src) -> dict:
        with self._lock:
            pending = self._pending_publish
            if pending is None or (pending["epoch"], pending["version"]) != (
                    payload["epoch"], payload["version"]):
                return {"ok": False}
            self._pending_publish = None
        self._apply_state(pending)
        return {"ok": True}

    def _apply_state(self, state: dict) -> None:
        with self._lock:
            deferred = self._apply_state_locked(state)
        # recovery + shard-started reporting run OUTSIDE the node lock
        # (but still synchronously, before the publish response returns):
        # they issue nested RPCs — a recovery's shard-started report makes
        # the master publish back to THIS node, which must be able to take
        # our lock. Holding it here deadlocks the cluster over TCP.
        for action in deferred:
            action()

    def _apply_state_locked(self, state: dict) -> list:
        """Adopt a published state (caller holds self._lock). Returns the
        deferred recovery/report actions, which the caller MUST run after
        releasing the lock."""
        epoch = state.get("epoch", 0)
        if epoch < self.cluster_epoch:
            return []  # publish from a deposed master — reject
        if epoch == self.cluster_epoch:
            if state["master"] == self.master_id:
                if state["version"] < self.state_version:
                    return []  # stale
            elif state["master"] > (self.master_id or ""):
                # two independent elections can reach the SAME epoch (each
                # bumps from its local value); break the tie like the
                # election does — the lower node id wins — so exactly one
                # side is rejected and the clusters can converge
                return []
        return self._adopt_state_locked(state)

    def _adopt_state_locked(self, state: dict) -> list:
        """Unconditionally take on `state` (no staleness checks — callers
        have already decided). Also the rollback primitive: a master whose
        commit quorum failed re-adopts its pre-change snapshot here."""
        self.cluster_epoch = state.get("epoch", 0)
        self.state_version = state["version"]
        self.master_id = state["master"]
        self.known_nodes = list(state["nodes"])
        self.indices_meta = {
            name: IndexMetadata(
                name, Settings(info["settings"]), info["mappings"],
                aliases=info.get("aliases") or {},
                state=info.get("state", "open"),
                creation_date=info.get("creation_date", 0),
                version=info.get("version", 1),
            )
            for name, info in state["indices"].items()
        }
        self.routing = routing_from_dict(state["routing"])
        self.primary_terms = {
            (key.rsplit("#", 1)[0], int(key.rsplit("#", 1)[1])): term
            for key, term in state.get("primary_terms", {}).items()
        }
        if state.get("node_info"):
            self.node_info_map = {
                n: dict(info) for n, info in state["node_info"].items()}
        return self._reconcile_shards()

    def _mapper_for(self, index: str) -> MapperService:
        if index not in self.mappers:
            md = self.indices_meta[index]
            self.mappers[index] = MapperService(
                AnalysisRegistry(md.settings), md.mappings
            )
        return self.mappers[index]

    def _reconcile_shards(self) -> list:
        """Create/remove/promote local shards to match the routing table
        (IndicesClusterStateService: createOrUpdateShards/removeShards).
        Returns deferred recovery/report actions for the caller to run
        after releasing the node lock (see _apply_state)."""
        deferred: list = []
        wanted: Dict[Tuple[str, int], ShardRouting] = {}
        for index, shards in self.routing.items():
            for sid, copies in shards.items():
                for copy in copies:
                    if copy.node_id == self.node_id:
                        wanted[(index, sid)] = copy
        # remove shards no longer ours; inside an uncommitted state
        # update the close is deferred so a failed commit quorum can
        # resurrect the shard with its data (see _submit_state_update)
        for key in list(self.shards):
            if key not in wanted or key[0] not in self.indices_meta:
                shard = self.shards.pop(key)
                if self._removed_shards is not None:
                    self._removed_shards.append((key, shard))
                else:
                    shard.close()
        # create / update
        for (index, sid), copy in wanted.items():
            shard = self.shards.get((index, sid))
            if shard is None:
                shard_path = (os.path.join(self.data_path, index, str(sid))
                              if self.data_path else None)
                shard = IndexShard(index, sid, self._mapper_for(index),
                                   data_path=shard_path,
                                   primary=copy.primary)
                if shard_path and (
                        shard.engine.store.read_commit() is not None
                        or os.path.exists(os.path.join(
                            shard_path, "translog", "translog.ckp"))):
                    try:
                        # restart over an existing data path: store load +
                        # translog replay bring back every acked write
                        shard.recover_from_store()
                    except CorruptIndexException as e:
                        # marked/corrupt bytes under the data path: never
                        # reload them. Quarantine the copy — a replica
                        # heals via peer recovery (the file pull wipes the
                        # directory and installs a verified set); a
                        # primary stays quarantined and fails reads
                        # loudly until a healthy copy takes over.
                        integrity_service().record_corruption(
                            index, sid, "load", str(e))
                        already = shard.engine.store.is_corrupted()
                        marker = shard.engine.store.mark_corrupted(
                            str(e), site="load")
                        if not already:
                            integrity_service().record_marker(
                                index, sid, marker, action="marked")
                        shard.store_corrupted = True
                        shard.start_fresh()
                else:
                    shard.start_fresh()
                if copy.primary:
                    from elasticsearch_tpu.index.seqno import GlobalCheckpointTracker

                    shard.checkpoints = GlobalCheckpointTracker(self.node_id)
                self.shards[(index, sid)] = shard
                if copy.state == ShardRoutingState.INITIALIZING:
                    if copy.primary:
                        # fresh primary starts empty and reports started
                        deferred.append(
                            lambda i=index, s=sid: self._report_started(i, s))
                    else:
                        deferred.append(
                            lambda i=index, s=sid: self._recover_replica(i, s))
            else:
                if copy.primary and not shard.primary:
                    # replica promoted: DRAIN in-flight ops, then adopt
                    # the master-assigned term (fencing) — everything
                    # after the permit barrier runs under the new term
                    # (IndexShardOperationPermits.blockOperations) — and
                    # seed a tracker from the routing table's started
                    # copies (reference: in-sync allocation ids from
                    # IndexMetaData); their checkpoints are unknown (-1)
                    # until the next write ack, keeping the global
                    # checkpoint conservative
                    shard.promote_to_primary(
                        self.primary_terms.get((index, sid), 1))
                    from elasticsearch_tpu.index.seqno import GlobalCheckpointTracker

                    tracker = GlobalCheckpointTracker(self.node_id)
                    tracker.seed_global_checkpoint(
                        shard.engine.global_checkpoint)
                    tracker.update_local_checkpoint(
                        self.node_id, shard.engine.local_checkpoint)
                    for other in self.routing.get(index, {}).get(sid, []):
                        if (other.node_id != self.node_id
                                and other.state == ShardRoutingState.STARTED):
                            tracker.mark_in_sync(other.node_id, -1, force=True)
                    shard.checkpoints = tracker
                    # post-failover warming (ISSUE 14): heat the promoted
                    # primary's search path off the query path
                    deferred.append(
                        lambda sh=shard: self._warm_promoted_primary(sh))
                elif copy.state == ShardRoutingState.INITIALIZING and not copy.primary:
                    deferred.append(
                        lambda i=index, s=sid: self._recover_replica(i, s))
            # every copy (primary or replica) adopts the published term so
            # equal-seqno tie-breaks and zombie-primary fencing work even
            # on copies that saw no write traffic from the new primary
            shard.primary_term = max(
                shard.primary_term, self.primary_terms.get((index, sid), 1))
            # prune tracker membership to the current routing copies: a
            # departed replica must not pin the global checkpoint
            tracker = getattr(shard, "checkpoints", None)
            if tracker is not None:
                tracker.prune({c.node_id
                               for c in self.routing.get(index, {}).get(sid, [])})
        return deferred

    def _primary_node(self, index: str, sid: int) -> Optional[str]:
        for copy in self.routing.get(index, {}).get(sid, []):
            if copy.primary:
                return copy.node_id
        return None

    # ------------------------------------------------------------------
    # Recovery (ops-based peer recovery, §3.5)
    # ------------------------------------------------------------------

    def _schedule_recovery_retry(self, index: str, sid: int,
                                 attempt: int) -> None:
        """Re-run a replica recovery that hit a transient race: the new
        primary's promotion can ride the SAME publish that assigned this
        INITIALIZING copy, so the source answers "not the primary" until
        it applies that state itself — and with no further state change
        coming, nothing would re-defer the recovery and the copy would
        park INITIALIZING forever. Bounded backoff, off the publish path
        (deferred actions run inside the commit RPC)."""
        if attempt >= 5:
            return

        def retry():
            copy = next((c for c in self.routing.get(index, {}).get(sid, [])
                         if c.node_id == self.node_id), None)
            if copy is None or copy.primary \
                    or copy.state != ShardRoutingState.INITIALIZING:
                return  # no longer ours to recover
            self._recover_replica(index, sid, _attempt=attempt + 1)

        t = threading.Timer(0.2 * (attempt + 1), retry)
        t.daemon = True
        t.start()

    def _recover_replica(self, index: str, sid: int,
                         _attempt: int = 0) -> None:
        primary_node = self._primary_node(index, sid)
        if primary_node is None or primary_node == self.node_id:
            self._schedule_recovery_retry(index, sid, _attempt)
            return
        # _cat/recovery progress (RecoveryState analog): one row per
        # copy, updated through every stage of this recovery. A RE-run
        # (the copy failed and recovers again) resets every counter —
        # the row describes THIS recovery, not the sum of attempts.
        record_recovery_progress(
            index, sid, self.node_id, source=primary_node, type="peer",
            stage="init", start_ms=int(time.time() * 1000), stop_ms=None,
            files_total=0, files_recovered=0, bytes_total=0,
            bytes_recovered=0, ops_total=0, ops_recovered=0)
        # phase1: copy the primary's committed segment files in chunks so
        # a fresh replica doesn't replay the whole history doc-by-doc;
        # any failure falls back to full ops replay (above_seqno = -1)
        above_seqno = -1
        try:
            above_seqno = self._pull_recovery_files(index, sid, primary_node)
        except CorruptIndexException as e:
            # corrupt bytes detected while installing the shipped set
            # (digest mismatch or checksum failure on install): retry the
            # whole session ONCE — transport-hop corruption is transient
            # and a fresh pull starts from a clean directory (PR-2 retry
            # machinery covers the per-chunk layer). A second failure
            # falls back to full ops replay, which rebuilds a correct
            # copy from the primary's live docs.
            integrity_service().record_corruption(
                index, sid, "recovery", str(e))
            try:
                above_seqno = self._pull_recovery_files(
                    index, sid, primary_node)
            except CorruptIndexException as e2:
                integrity_service().record_corruption(
                    index, sid, "recovery", str(e2))
                above_seqno = -1
            except (NodeNotConnectedException, ElasticsearchTpuException,
                    OSError, ValueError):
                above_seqno = -1
        except (NodeNotConnectedException, ElasticsearchTpuException,
                OSError, ValueError):
            above_seqno = -1
        record_recovery_progress(index, sid, self.node_id,
                                 stage="translog")
        try:
            resp = self.transport.send_request(
                primary_node, ACTION_RECOVER, {
                    "index": index, "shard": sid, "target": self.node_id,
                    "above_seqno": above_seqno,
                },
                timeout=self.recovery_action_timeout,
                retry=self.recovery_retry)
        except (NodeNotConnectedException, ElasticsearchTpuException):
            # retries with backoff exhausted — often the publish-ordering
            # race above (source not yet primary): retry off-path
            self._schedule_recovery_retry(index, sid, _attempt)
            return
        # recovery runs outside the node lock (deferred from
        # _apply_state): a concurrent newer state may have removed the
        # local copy in the meantime — bail instead of KeyError-ing
        # through the publish RPC
        shard = self.shards.get((index, sid))
        if shard is None:
            return
        record_recovery_progress(index, sid, self.node_id,
                                 add_ops_total=len(resp["ops"]))
        for op in resp["ops"]:
            self._apply_replicated_op(shard, op)
            record_recovery_progress(index, sid, self.node_id,
                                     add_ops_recovered=1)
        shard.refresh()
        # confirm the replay to the primary (recovery finalize) so it can
        # mark this copy in-sync at a checkpoint we actually hold; the
        # response carries the ops written since the stream snapshot
        # finalize loop: confirm our checkpoint, apply the returned delta,
        # repeat until the delta is empty so the primary has seen a
        # caught-up checkpoint and promotes us out of pending-in-sync
        # even if no further writes arrive (reference: pendingInSync wait
        # in markAllocationIdAsInSync)
        record_recovery_progress(index, sid, self.node_id,
                                 stage="finalize")
        for _round in range(5):
            fin = None
            try:  # transient faults retry with backoff (RetryableAction)
                fin = self.transport.send_request(
                    primary_node, ACTION_RECOVERY_FINALIZE, {
                        "index": index, "shard": sid,
                        "local_checkpoint": shard.engine.local_checkpoint,
                    },
                    timeout=self.recovery_action_timeout,
                    retry=self.recovery_retry)
            except (NodeNotConnectedException, ElasticsearchTpuException):
                pass
            if fin is None:
                # primary unreachable: stay INITIALIZING; the bounded
                # backoff (or the next publish / master health check)
                # re-runs the recovery from the top — it is idempotent
                self._schedule_recovery_retry(index, sid, _attempt)
                return
            if not fin.get("ops"):
                break
            # delta ops may race with the live write fan-out (this copy is
            # already in the primary's replication group); the engine's
            # seqno staleness guard makes the apply idempotent in either
            # order
            record_recovery_progress(index, sid, self.node_id,
                                     add_ops_total=len(fin["ops"]))
            for op in fin["ops"]:
                self._apply_replicated_op(shard, op)
                record_recovery_progress(index, sid, self.node_id,
                                         add_ops_recovered=1)
            shard.refresh()
        record_recovery_progress(index, sid, self.node_id, stage="done",
                                 stop_ms=int(time.time() * 1000))
        self._report_started(index, sid)

    @staticmethod
    def _apply_replicated_op(shard, op: dict) -> None:
        """Apply one replicated/recovery op (explicit seqno + version from
        the primary); the engine's seqno staleness guard makes this
        idempotent under redelivery and reordering."""
        if op["op"] == "delete":
            shard.engine.delete(op["id"], seqno=op["seq_no"],
                                replicated_version=op.get("version"),
                                primary_term=op.get("primary_term", 1))
        else:
            shard.engine.index(op["id"], op["source"], op.get("routing"),
                               seqno=op["seq_no"],
                               replicated_version=op.get("version"),
                               primary_term=op.get("primary_term", 1))

    def _on_start_recovery(self, payload, src) -> dict:
        """Primary side: stream live docs as seqno-stamped ops — phase2
        replay, above the seqno the file phase already shipped (or the
        whole history when there was no file phase: above_seqno = -1)."""
        shard = self.shards.get((payload["index"], payload["shard"]))
        if shard is None or not shard.primary:
            raise ElasticsearchTpuException(
                f"recovery source is not the primary for "
                f"[{payload['index']}][{payload['shard']}]"
            )
        shard.refresh()
        ops = self._collect_ops(shard, payload.get("above_seqno", -1))
        # the target is tracked (not yet in-sync) until it confirms the
        # replay via the finalize RPC (_on_recovery_finalize)
        tracker = getattr(shard, "checkpoints", None)
        if tracker is not None:
            tracker.initiate_tracking(src)
        return {"ops": ops, "max_seq_no": shard.engine.max_seqno}

    # --- phase1: segment-file shipping (RecoverySourceHandler.phase1) ---

    def _on_start_file_recovery(self, payload, src) -> dict:
        """Primary side: flush a commit, snapshot the store's files, and
        open a chunked-transfer session. The target copies segment files
        instead of replaying the whole history doc-by-doc
        (indices/recovery/RecoverySourceHandler.java:165)."""
        shard = self.shards.get((payload["index"], payload["shard"]))
        if shard is None or not shard.primary:
            raise ElasticsearchTpuException(
                f"recovery source is not the primary for "
                f"[{payload['index']}][{payload['shard']}]")
        store = shard.engine.store
        if store.is_corrupted():
            # a marked copy must never be a recovery source: shipping its
            # bytes would propagate the corruption to a healthy target
            raise ElasticsearchTpuException(
                f"recovery source [{payload['index']}][{payload['shard']}]"
                f" on [{self.node_id}] is marked corrupted")
        shard.flush()  # durable commit: segments + tombstones + terms
        commit = store.read_commit() or {}
        files: Dict[str, bytes] = {}
        base = store.directory
        from elasticsearch_tpu.index.store import MARKER_PREFIX
        for root, _dirs, names in os.walk(base):
            for name in names:
                if (root == base and name.startswith(MARKER_PREFIX)
                        and name.endswith(".json")):
                    continue  # corruption markers never ship
                full = os.path.join(root, name)
                rel = os.path.relpath(full, base)
                with open(full, "rb") as f:
                    files[rel] = f.read()
        with self._lock:
            # reclaim sessions whose targets went silent (died mid-pull)
            now = time.monotonic()
            for key in [k for k, v in self._recovery_sessions.items()
                        if now - v.get("last_used", v["t0"])
                        > RECOVERY_SESSION_MAX_AGE_S]:
                del self._recovery_sessions[key]
            self._recovery_session_seq += 1
            session = (f"{payload['index']}_{payload['shard']}_{src}_"
                       f"{self._recovery_session_seq}")
            self._recovery_sessions[session] = {
                "files": files, "t0": time.monotonic(),
                "last_used": time.monotonic(), "sent": 0, "target": src,
            }
        # per-file SHA-256 digests ride the manifest (ISSUE 16): the
        # target verifies every installed file against the SOURCE's
        # digest before adopting the set — the transport/disk hop can
        # never silently corrupt a copy
        manifest = [{"path": p, "size": len(b),
                     "digest": hashlib.sha256(b).hexdigest()}
                    for p, b in files.items()]
        return {"session": session, "files": manifest,
                "max_seq_no": int(commit.get("max_seq_no", -1))}

    def _on_recovery_file_chunk(self, payload, src) -> dict:
        with self._lock:
            sess = self._recovery_sessions.get(payload["session"])
        if sess is None:
            raise ElasticsearchTpuException(
                f"unknown recovery session [{payload['session']}]")
        data = sess["files"].get(payload["path"])
        if data is None:
            raise ElasticsearchTpuException(
                f"unknown recovery file [{payload['path']}]")
        off = int(payload.get("offset", 0))
        length = min(int(payload.get("length", RECOVERY_CHUNK_BYTES)),
                     RECOVERY_CHUNK_BYTES)
        chunk = data[off: off + length]
        sess["sent"] += len(chunk)
        sess["last_used"] = time.monotonic()
        # source-side throttle (indices.recovery.max_bytes_per_sec):
        # sleep the FULL deficit so low rates are actually honored (a
        # capped single sleep would floor the effective rate at
        # chunk_size / cap regardless of the setting)
        rate = self.recovery_max_bytes_per_sec
        if rate:
            ahead = sess["sent"] / rate - (time.monotonic() - sess["t0"])
            if ahead > 0:
                time.sleep(min(ahead, 30.0))
        return {"data": base64.b64encode(chunk).decode("ascii"),
                "eof": off + len(chunk) >= len(data)}

    def _close_recovery_sessions(self, index: str, sid: int,
                                 target: str) -> None:
        prefix = f"{index}_{sid}_{target}_"
        with self._lock:
            for key in [k for k in self._recovery_sessions
                        if k.startswith(prefix)]:
                del self._recovery_sessions[key]

    def _pull_recovery_files(self, index: str, sid: int,
                             primary_node: str) -> int:
        """Target side of phase1: open a session on the primary, pull
        every committed file in chunks into the local store, and install
        the segments (store load + version map + tombstone adoption).
        Returns the max seqno contained in the shipped files (the phase2
        replay floor). Raises on any mismatch; the caller falls back to
        full ops replay."""
        shard = self.shards.get((index, sid))
        if shard is None:
            raise ElasticsearchTpuException("local copy vanished")
        start = self.transport.send_request(
            primary_node, ACTION_RECOVER_FILES_START, {
                "index": index, "shard": sid, "target": self.node_id},
            timeout=self.recovery_action_timeout,
            retry=self.recovery_retry)
        if not start.get("files") or start.get("max_seq_no", -1) < 0:
            return -1  # empty primary: nothing to ship, pure ops replay
        record_recovery_progress(
            index, sid, self.node_id, stage="index",
            files_total=len(start["files"]),
            bytes_total=sum(int(e["size"]) for e in start["files"]))
        try:
            return self._pull_session_files(shard, start, primary_node)
        except BaseException:
            # abort: tear the source-side session down NOW instead of
            # leaving a full file snapshot pinned until the age-based
            # reclaim (the reference cancels the recovery and releases
            # its IndexCommit ref the same way); best-effort — the
            # age-based sweep remains the backstop
            try:
                self.transport.send_request(
                    primary_node, ACTION_RECOVER_FILES_CLOSE,
                    {"session": start["session"]},
                    timeout=self.recovery_action_timeout)
            except (NodeNotConnectedException, ElasticsearchTpuException):
                pass
            raise

    def _pull_session_files(self, shard, start: dict,
                            primary_node: str) -> int:
        store = shard.engine.store
        # capture markers before the wipe: a successful install below is
        # the ONE legal transition out of quarantine, and the clears must
        # land in the integrity event ring (ISSUE 16)
        prior_markers = store.corruption_markers()
        # a retry may leave partial files behind — start clean
        shutil.rmtree(store.directory, ignore_errors=True)
        os.makedirs(store.directory, exist_ok=True)
        for entry in start["files"]:
            rel, size = entry["path"], entry["size"]
            full = os.path.join(store.directory, rel)
            os.makedirs(os.path.dirname(full) or store.directory,
                        exist_ok=True)
            with open(full, "wb") as f:
                offset = 0
                while offset < size:
                    # chunk pulls retry with backoff: chunks are offset-
                    # addressed reads of an immutable snapshot, so a
                    # redelivered chunk is byte-identical
                    chunk = self.transport.send_request(
                        primary_node, ACTION_RECOVER_FILE_CHUNK, {
                            "session": start["session"], "path": rel,
                            "offset": offset,
                            "length": RECOVERY_CHUNK_BYTES},
                        timeout=self.recovery_action_timeout,
                        retry=self.recovery_retry)
                    data = base64.b64decode(chunk["data"])
                    if not data and not chunk.get("eof"):
                        raise ElasticsearchTpuException(
                            f"empty non-final chunk for [{rel}]")
                    f.write(data)
                    offset += len(data)
                    record_recovery_progress(
                        shard.index_name, shard.shard_id, self.node_id,
                        add_bytes_recovered=len(data))
                    if chunk.get("eof"):
                        break
            if os.path.getsize(full) != size:
                raise ElasticsearchTpuException(
                    f"short file [{rel}]: {os.path.getsize(full)} != {size}")
            # verify the installed bytes against the SOURCE's digest
            # before adopting (Lucene verifies checksums on every file
            # adoption the same way) — a mismatch is corruption in
            # flight, caught before recover_from_store can read it
            expected = entry.get("digest")
            if expected is not None:
                with open(full, "rb") as rf:
                    actual = hashlib.sha256(rf.read()).hexdigest()
                if actual != expected:
                    raise CorruptIndexException(
                        f"recovery file [{rel}] digest mismatch "
                        f"(source={expected[:12]}, installed={actual[:12]})")
            record_recovery_progress(shard.index_name, shard.shard_id,
                                     self.node_id, add_files_recovered=1)
        # install: load the shipped commit (verifies per-segment
        # checksums), rebuild the version map and tombstones — the same
        # path a restarting node uses (IndexShard.recover_from_store)
        shard.recover_from_store()
        # the verified set is installed: the copy leaves quarantine
        for marker in prior_markers:
            integrity_service().record_marker(
                shard.index_name, shard.shard_id, marker, action="cleared")
        shard.store_corrupted = False
        return int(start["max_seq_no"])

    def _on_recovery_files_close(self, payload, src) -> dict:
        """Source side: the target aborted its file pull — free the
        session's snapshot bytes immediately."""
        with self._lock:
            self._recovery_sessions.pop(payload["session"], None)
        return {"ok": True}

    @staticmethod
    def _collect_ops(shard, above_seqno: int = -1) -> list:
        """Live docs as seqno-stamped index ops (> above_seqno), plus
        delete tombstones. Tombstones are ALWAYS included: a recovery
        re-run hits a target that may already hold state from a previous
        attempt (ops the staleness guard will noop-skip), so omitting
        deletes would resurrect docs the primary removed between
        attempts."""
        ops = []
        vmap = shard.engine.version_map
        for seg in shard.engine.searchable_segments():
            for local in range(seg.num_docs):
                if seg.live[local] and int(seg.seqnos[local]) > above_seqno:
                    entry = vmap.get(seg.doc_ids[local])
                    ops.append({
                        "op": "index",
                        "id": seg.doc_ids[local],
                        "source": seg.sources[local],
                        "routing": seg.routings[local],
                        "seq_no": int(seg.seqnos[local]),
                        "version": int(seg.versions[local]),
                        "primary_term": entry.term if entry is not None else 1,
                    })
        for doc_id, entry in vmap.items():
            if getattr(entry, "deleted", False) and entry.seqno > above_seqno:
                ops.append({"op": "delete", "id": doc_id,
                            "seq_no": int(entry.seqno),
                            "version": int(entry.version),
                            "primary_term": entry.term})
        ops.sort(key=lambda op: op["seq_no"])
        return ops

    @staticmethod
    def _delta_ops(shard, above_seqno: int) -> list:
        """Ops with seqno > above_seqno for the finalize delta. Prefers a
        translog read (cheap, no refresh, no index scan under the
        replication lock); falls back to the full segment scan when the
        translog no longer retains that range (trimmed by a flush)."""
        from elasticsearch_tpu.index.translog import TranslogOp

        tl = shard.engine.translog
        if above_seqno >= tl.committed_seqno:
            return [op.to_dict() for op in tl.snapshot(above_seqno + 1)
                    if op.op_type != TranslogOp.NO_OP]
        shard.refresh()
        return ClusterNode._collect_ops(shard, above_seqno=above_seqno)

    def _on_recovery_finalize(self, payload, src) -> dict:
        """Primary side: the target applied the streamed ops — return the
        delta written since the stream snapshot, then mark the copy
        in-sync (RecoverySourceHandler finalize ->
        markAllocationIdAsInSync). From in-sync on, the write fan-out
        covers the copy even before the master publishes STARTED, so no
        op can fall into the finalize->STARTED window."""
        with self._replication_lock:  # serialize vs _on_write_primary: no
            # op may land between the delta snapshot and the in-sync mark
            shard = self.shards.get((payload["index"], payload["shard"]))
            tracker = getattr(shard, "checkpoints", None) if shard else None
            delta = []
            if shard is not None:
                delta = self._delta_ops(shard, payload["local_checkpoint"])
            if tracker is not None:
                # credit only what the target confirmed; the delta is
                # applied after this RPC returns and the next write ack
                # advances the checkpoint
                tracker.mark_in_sync(src, payload["local_checkpoint"])
        # phase1 file session no longer needed once the target reached
        # the finalize stage — free the snapshot bytes
        self._close_recovery_sessions(payload["index"], payload["shard"], src)
        return {"ok": True, "ops": delta}

    def _report_started(self, index: str, sid: int) -> None:
        try:
            self.transport.send_request(
                self.master_id, ACTION_SHARD_STARTED, {
                    "index": index, "shard": sid, "node": self.node_id,
                },
                timeout=self.request_timeout, retry=self.retry_policy)
        except NodeNotConnectedException:
            pass
        except FailedToCommitClusterStateException:
            # the master could not commit the started-state; it rolled
            # back and stepped down. Swallow: the next elected master
            # re-allocates and this copy re-reports. Propagating would
            # crash the applier loop that triggered the recovery. (When
            # the report ran as a deferred action inside our OWN
            # _submit_state_update, swallowing is still safe: the outer
            # publish independently hits the same dead quorum and rolls
            # the outer change back.)
            pass

    def _on_shard_started(self, payload, src) -> dict:
        with self._lock:
            if not self.is_master:
                return {"ok": False}

        def mutate():
            if not self.is_master:
                raise NotMasterException("master changed")
            for copy in self.routing.get(
                    payload["index"], {}).get(payload["shard"], []):
                if copy.node_id == payload["node"]:
                    copy.state = ShardRoutingState.STARTED

        try:
            self._submit_state_update(mutate)
        except NotMasterException:
            # mastership moved between the pre-check and the locked
            # mutate: answer the benign no-op the reporter expects
            # instead of raising across the RPC
            return {"ok": False}
        return {"ok": True}

    def _on_shard_failed(self, payload, src) -> dict:
        """Primary reports a failed replica copy; master drops it from the
        routing table and reroutes (ShardStateAction.shardFailed)."""
        with self._lock:
            if not self.is_master:
                return {"ok": False}

        def mutate():
            if not self.is_master:
                raise NotMasterException("master changed")
            if payload["index"] not in self.routing:
                # the index was deleted while the report was in flight —
                # a benign no-op, not a crash across the reporter's RPC
                raise NotMasterException("index no longer routed")
            copies = self.routing[payload["index"]].get(payload["shard"], [])
            if payload.get("corrupt"):
                key = (payload["index"], payload["shard"])
                survivors = [
                    c for c in copies
                    if c.node_id != payload["node"]
                    and c.state == ShardRoutingState.STARTED]
                if not survivors:
                    # LAST-copy corruption: dropping it would let the
                    # allocator fill a fresh EMPTY primary — silent
                    # data-loss resurrection. Retain the copy routed
                    # (quarantined on its node, every read fails loudly)
                    # and surface the marker via allocation explain /
                    # _cat/shards until an operator restores a snapshot
                    # or the bytes are repaired out of band.
                    self.corrupt_retained[key] = {
                        "node": payload["node"],
                        "reason": payload.get("reason", ""),
                    }
                    raise NotMasterException("last copy retained")
                self.corrupt_retained.pop(key, None)
            self.routing[payload["index"]][payload["shard"]] = [
                c for c in copies if c.node_id != payload["node"]
            ]

        try:
            self._submit_state_update(mutate)
        except NotMasterException:
            return {"ok": False}
        return {"ok": True}

    # ------------------------------------------------------------------
    # Write path (ReplicationOperation, §3.3)
    # ------------------------------------------------------------------

    def _on_write_primary(self, payload, src) -> dict:
        with self._replication_lock:  # pairs with _on_recovery_finalize
            result, failed_copies = self._write_primary_locked(payload, src)
        # report failed copies OUTSIDE the lock: the master's publish can
        # re-enter other nodes' locks and must not nest under ours
        for node_id in failed_copies:
            try:
                self.transport.send_request(
                    self.master_id, ACTION_SHARD_FAILED, {
                        "index": payload["index"],
                        "shard": payload["shard"],
                        "node": node_id,
                    },
                    timeout=self.request_timeout, retry=self.report_retry)
            except FailedToCommitClusterStateException:
                # a master that could not commit the copy-removal rolled
                # back and stepped down; the re-elected master's epoch
                # fences the old cluster and reconciliation re-runs —
                # the write keeps its ack (same rationale as
                # _report_started)
                pass
            except NodeNotConnectedException as e:
                # the failed copy could NOT be reported: the routing
                # table still lists it STARTED, so a later promotion
                # could pick the diverged copy and lose this op. The
                # reference fails the primary rather than ack
                # (ReplicationOperation.onNoLongerPrimary) — surface
                # the uncertainty so the coordinator retries the write
                # instead of treating it as durably replicated.
                raise ElasticsearchTpuException(
                    f"replica [{node_id}] failed for "
                    f"[{payload['index']}][{payload['shard']}] but the "
                    f"failure could not be reported to the master; the "
                    f"write is not fully replicated") from e
        return result

    def _write_primary_locked(self, payload, src) -> dict:
        index, sid = payload["index"], payload["shard"]
        shard = self.shards.get((index, sid))
        if shard is None or not shard.primary:
            raise ElasticsearchTpuException(
                f"[{index}][{sid}] primary is not allocated on [{self.node_id}]"
            )
        copies = self.routing.get(index, {}).get(sid, [])
        wfas = payload.get("wait_for_active_shards")
        if wfas is not None:
            from elasticsearch_tpu.index.seqno import check_active_shards

            active = sum(1 for c in copies
                         if c.state == ShardRoutingState.STARTED)
            check_active_shards(wfas, active, len(copies), f"[{index}][{sid}]")
        # primary operation permit (IndexShard.java:2089): fences ops the
        # coordinator routed under a superseded term AND holds the permit
        # a promotion/handoff drain waits on
        with shard.acquire_primary_permit(payload.get("term")):
            if payload["op"] == "index":
                result = shard.index_doc(payload["id"], payload["source"],
                                         payload.get("routing"))
            else:
                result = shard.delete_doc(payload["id"])
        # track the primary's own checkpoint, then fan out to replicas with
        # the primary-assigned seqno/version + the current global checkpoint
        # (piggybacked like the reference's replication requests)
        tracker = getattr(shard, "checkpoints", None)
        if tracker is not None:
            tracker.update_local_checkpoint(self.node_id,
                                            shard.engine.local_checkpoint)
        replica_payload = dict(payload)
        replica_payload["seq_no"] = result["_seq_no"]
        replica_payload["version"] = result["_version"]
        replica_payload["primary_term"] = shard.primary_term
        replica_payload["global_checkpoint"] = (
            tracker.global_checkpoint if tracker is not None else -1)
        acks = 1
        failed_copies = []
        for copy in self.routing.get(index, {}).get(sid, []):
            if copy.primary:
                continue
            # replication group = STARTED copies + copies already marked
            # in-sync by recovery finalize (the master may not have
            # published STARTED yet; skipping them would lose the ops
            # written in that window)
            in_sync = tracker is not None and (
                copy.node_id in tracker.in_sync
                or copy.node_id in tracker.pending_in_sync)
            if copy.state != ShardRoutingState.STARTED and not in_sync:
                continue
            try:
                # deadline + bounded retries: a lagging or blackholed
                # replica costs at most the replication timeout, then is
                # FAILED (removed from in-sync, reported to the master
                # for reroute) while the primary keeps serving — the
                # replicated op is seqno-stamped, so retries are
                # idempotent under redelivery
                ack = self.transport.send_request(
                    copy.node_id, ACTION_WRITE_REPLICA, replica_payload,
                    timeout=self.replication_timeout,
                    retry=self.replication_retry)
                acks += 1
                if tracker is not None:
                    tracker.update_local_checkpoint(
                        copy.node_id, ack.get("local_checkpoint", -1))
            except (NodeNotConnectedException, ElasticsearchTpuException):
                # shrink the in-sync set now; the master report happens
                # outside the replication lock (§5.3)
                if tracker is not None:
                    tracker.remove(copy.node_id)
                failed_copies.append(copy.node_id)
        if tracker is not None:
            shard.engine.global_checkpoint = tracker.global_checkpoint
        result["_shards"] = {"total": len(self.routing.get(index, {}).get(sid, [])),
                             "successful": acks, "failed": len(failed_copies)}
        if failed_copies:
            # ReplicationResponse.ShardInfo: per-copy failure details
            result["_shards"]["failures"] = [
                {"_index": index, "_shard": sid, "_node": node_id,
                 "status": "INTERNAL_SERVER_ERROR", "primary": False,
                 "reason": {"type": "replication_failed_exception",
                            "reason": f"failed to replicate to [{node_id}]"}}
                for node_id in failed_copies
            ]
        return result, failed_copies

    def _on_write_replica(self, payload, src) -> dict:
        shard = self.shards.get((payload["index"], payload["shard"]))
        if shard is None:
            raise ElasticsearchTpuException(
                f"replica shard [{payload['index']}][{payload['shard']}] not "
                f"allocated on [{self.node_id}]"
            )
        if payload.get("primary_term", 1) < shard.primary_term:
            # stale primary (fencing, IndexShardOperationPermits analog)
            raise ElasticsearchTpuException("operation primary term is too old")
        # learn a newer term from write traffic too — the publish that
        # carries it may still be in flight
        shard.primary_term = max(shard.primary_term,
                                 payload.get("primary_term", 1))
        self._apply_replicated_op(shard, payload)
        # learn the primary's global checkpoint; report our local one back
        shard.engine.global_checkpoint = max(
            shard.engine.global_checkpoint,
            payload.get("global_checkpoint", -1))
        return {"ok": True,
                "local_checkpoint": shard.engine.local_checkpoint}

    # ------------------------------------------------------------------
    # Read path
    # ------------------------------------------------------------------

    def _on_get(self, payload, src) -> dict:
        shard = self.shards.get((payload["index"], payload["shard"]))
        if shard is None:
            raise ElasticsearchTpuException("shard not allocated here")
        g = shard.get_doc(payload["id"])
        return {
            "found": g.found,
            "_id": payload["id"],
            "_source": g.source,
            "_version": g.version,
            "_seq_no": g.seqno,
        }

    def _on_query(self, payload, src) -> dict:
        shard = self.shards.get((payload["index"], payload["shard"]))
        if shard is None:
            raise ElasticsearchTpuException("shard not allocated here")
        body = payload["body"] or {}
        from elasticsearch_tpu.search.service import fetch_hits
        from elasticsearch_tpu.search.telemetry import (
            get_opaque_id,
            set_opaque_id,
        )

        # the coordinator's task headers ride the transport hop (the
        # reference forwards threadContext headers on every internal
        # action): the data node's slowlog/profile lines join to the
        # ORIGINATING client's X-Opaque-Id, not to nothing (PR 8 closed
        # this for single-node only)
        headers = payload.get("headers") or {}
        prev_oid = get_opaque_id()
        set_opaque_id(headers.get("X-Opaque-Id") or prev_oid)
        try:
            if getattr(shard, "store_corrupted", False):
                # quarantined copy: fail fast (no re-read of marked
                # bytes, no re-detection) — the coordinator fails over
                # to the next ranked copy (ClusterClient.search)
                raise CorruptIndexException(
                    f"shard [{payload['index']}][{payload['shard']}] "
                    f"copy on [{self.node_id}] is marked corrupted")
            try:
                result = shard.searcher.query(body,
                                              size_hint=payload.get("k", 10))
                hits = fetch_hits(result.refs, {shard.shard_id: shard},
                                  body, payload["index"])
            except CorruptIndexException as e:
                # first detection on the cluster query path: quarantine
                # this copy and tell the master so a healthy copy takes
                # over (promotion / re-recovery); re-raise so the
                # coordinator's failover walk tries the next copy — the
                # PR-4 partial contract, never a silent wrong result
                self._fail_corrupted_copy(
                    payload["index"], payload["shard"], shard, e)
                raise
        finally:
            set_opaque_id(prev_oid)
        for ref, hit in zip(result.refs, hits):
            hit["_sort_tuple"] = list(ref.sort_values)
        return {
            "total": result.total_hits,
            "max_score": result.max_score,
            "hits": hits,
        }

    def _fail_corrupted_copy(self, index: str, sid: int, shard,
                             exc: Exception) -> None:
        """Local quarantine + master report for a corrupt copy detected
        on the serve path (ISSUE 16): write the marker, flag the shard,
        release its device staging through the accountant (ledger exact
        — a quarantined copy must not pin HBM), then report our own copy
        failed with the corrupt flag so the master heals — replica:
        re-recover from the primary; primary: fail over to a STARTED
        replica; last copy: retained quarantined (RED), never replaced
        with a fresh empty primary."""
        store = shard.engine.store
        integ = integrity_service()
        integ.record_corruption(index, sid, "query", str(exc))
        already = store.is_corrupted()
        marker = store.mark_corrupted(str(exc), site="query")
        if not already:
            integ.record_marker(index, sid, marker, action="marked")
        shard.store_corrupted = True
        for seg in list(shard.engine.segments):
            try:
                seg.release_device_staging()
            except Exception:  # noqa: BLE001 — release is best-effort
                pass  # shard close's release backstop covers it
        try:
            self.transport.send_request(
                self.master_id, ACTION_SHARD_FAILED, {
                    "index": index, "shard": sid, "node": self.node_id,
                    "corrupt": True, "reason": str(exc)[:200],
                },
                timeout=self.request_timeout, retry=self.report_retry)
        except (NodeNotConnectedException, ElasticsearchTpuException,
                FailedToCommitClusterStateException):
            # unreachable/stepped-down master: the copy stays quarantined
            # locally (queries fail over); the next master health pass or
            # state publish re-reports through reconciliation
            pass

    def _on_refresh(self, payload, src) -> dict:
        shard = self.shards.get((payload["index"], payload["shard"]))
        if shard is not None:
            shard.refresh()
        return {"ok": True}

    def _warm_promoted_primary(self, shard) -> None:
        """Post-failover promotion warming (ISSUE 14): heat the promoted
        primary's search path in the background, off the query path —
        the first client search after a promotion must not pay the cold
        path (compile_cache.warming marks any first compile as warmed)."""
        def warm():
            from elasticsearch_tpu.common.compile_cache import warming

            try:
                with warming():
                    shard.searcher.query({"query": {"match_all": {}}},
                                         size_hint=1)
            except Exception:  # noqa: BLE001 — warming is best-effort
                pass

        threading.Thread(target=warm, daemon=True,
                         name=f"promote-warm[{shard.index_name}]"
                              f"[{shard.shard_id}]").start()

    def close(self, graceful: bool = True) -> None:
        """Shutdown ordering (ISSUE 14): durable synced-flush marker
        first (warm restart over this data path recovers ops-free),
        then the graceful-leave announcement (peers reroute and promote
        NOW), then transport deregistration BEFORE the shards close —
        a closing node must never receive and half-serve a routed
        request mid-teardown."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        if getattr(self, "_fd_stop", None) is not None:
            self._fd_stop.set()
        if self.data_path:
            for shard in list(self.shards.values()):
                try:
                    shard.synced_flush()
                except Exception:  # noqa: BLE001 — flush is best-effort
                    pass  # at shutdown; translog replay covers the gap
        if graceful:
            try:
                self.graceful_leave()
            except Exception:  # noqa: BLE001 — fall back to FD removal
                pass
        self.transport.close()
        for shard in list(self.shards.values()):
            shard.close()


class ClusterClient:
    """Coordinator-side API over the cluster (any node can coordinate —
    here the client picks routes directly from its node's state copy)."""

    def __init__(self, node: ClusterNode):
        self.node = node
        # adaptive replica selection: rank copies by observed latency
        # (node/ResponseCollectorService.java)
        from elasticsearch_tpu.cluster.response_collector import (
            ResponseCollectorService,
        )

        self.response_collector = ResponseCollectorService()

    def _timed_request(self, node_id: str, action: str, payload):
        self.response_collector.on_send(node_id)
        t0 = time.monotonic()
        try:
            resp = self.node.transport.send_request(
                node_id, action, payload,
                timeout=self.node.request_timeout)
            # successes feed the EWMA; failures go through the penalty
            # path below — a dead node's instant connection error must
            # not earn it the best rank
            self.response_collector.add_response_time(
                node_id, time.monotonic() - t0)
            return resp
        except Exception:
            # unreachable copy OR a remote query-phase failure: penalize
            # its rank either way, so adaptive replica selection reroutes
            # reads away from a copy that keeps erroring (a corrupt
            # replica must not stay first in every failover walk)
            self.response_collector.on_failure(
                node_id, time.monotonic() - t0)
            raise
        finally:
            self.response_collector.on_complete(node_id)

    def _routing_entry(self, index: str, doc_id: str,
                       routing: Optional[str]) -> Tuple[int, str]:
        md = self.node.indices_meta.get(index)
        if md is None:
            raise IndexNotFoundException(index)
        sid = shard_id_for(routing if routing is not None else doc_id,
                           md.num_shards)
        primary = self.node._primary_node(index, sid)
        if primary is None:
            raise ElasticsearchTpuException(
                f"primary shard [{index}][{sid}] is unassigned"
            )
        return sid, primary

    def index(self, index: str, doc_id: str, source: dict,
              routing: Optional[str] = None,
              wait_for_active_shards=None) -> dict:
        sid, primary = self._routing_entry(index, doc_id, routing)
        # deadline only, NO retry: re-sending a primary write after a
        # timeout could double-apply it (the op has no client-side
        # idempotency token); the uncertainty surfaces to the caller
        return self.node.transport.send_request(primary, ACTION_WRITE_PRIMARY, {
            "op": "index", "index": index, "shard": sid, "id": doc_id,
            "source": source, "routing": routing,
            "wait_for_active_shards": wait_for_active_shards,
            # the coordinator's view of the primary term rides along so
            # the primary's operation permit can fence ops routed under
            # a superseded term (TransportReplicationAction carries the
            # primary term the same way)
            "term": self.node.primary_terms.get((index, sid)),
        }, timeout=self.node.request_timeout)

    def delete(self, index: str, doc_id: str) -> dict:
        sid, primary = self._routing_entry(index, doc_id, None)
        return self.node.transport.send_request(primary, ACTION_WRITE_PRIMARY, {
            "op": "delete", "index": index, "shard": sid, "id": doc_id,
            "term": self.node.primary_terms.get((index, sid)),
        }, timeout=self.node.request_timeout)

    def get(self, index: str, doc_id: str, prefer_replica: bool = False) -> dict:
        md = self.node.indices_meta.get(index)
        if md is None:
            raise IndexNotFoundException(index)
        sid = shard_id_for(doc_id, md.num_shards)
        copies = [c for c in self.node.routing[index][sid]
                  if c.state == ShardRoutingState.STARTED]
        if prefer_replica:
            copies.sort(key=lambda c: c.primary)
        else:
            # adaptive replica selection: best-ranked copy first, primary
            # breaking ties
            copies = self.response_collector.order_copies(copies)
        for copy in copies:
            try:
                return self._timed_request(copy.node_id, ACTION_GET, {
                    "index": index, "shard": sid, "id": doc_id,
                })
            except NodeNotConnectedException:
                continue
        raise ElasticsearchTpuException(f"no available copy for [{index}][{sid}]")

    def refresh(self, index: str) -> None:
        for sid, copies in self.node.routing.get(index, {}).items():
            for copy in copies:
                try:
                    self.node.transport.send_request(copy.node_id, ACTION_REFRESH, {
                        "index": index, "shard": sid,
                    }, timeout=self.node.request_timeout)
                except NodeNotConnectedException:
                    pass

    def search(self, index: str, body: Optional[dict] = None) -> dict:
        """Scatter-gather across one STARTED copy per shard (§3.2).

        Per-shard isolation (AbstractSearchAsyncAction.onShardFailure):
        a copy that fails — connection loss OR a query-phase exception on
        the remote shard — fails over to the next ranked copy; a shard
        with no surviving copy becomes a failures[] entry and the
        response degrades to partial results (HTTP 200, _shards.failed),
        unless allow_partial_search_results=false."""
        from elasticsearch_tpu.common.errors import (
            SearchPhaseExecutionException,
        )
        from elasticsearch_tpu.search.service import (
            allow_partial_results,
            shard_failure_entry,
        )

        body = body or {}
        if "allow_partial_search_results" not in body and \
                not S.SEARCH_ALLOW_PARTIAL_RESULTS.get(self.node.settings):
            body = dict(body)
            body["allow_partial_search_results"] = False
        md = self.node.indices_meta.get(index)
        if md is None:
            raise IndexNotFoundException(index)
        # coordinator → data-node task headers: the client's X-Opaque-Id
        # crosses the transport hop with the per-shard query actions so
        # remote slowlog/profile lines join to the originating client
        from elasticsearch_tpu.search.telemetry import get_opaque_id

        opaque_id = get_opaque_id()
        hop_headers = ({"X-Opaque-Id": opaque_id} if opaque_id else None)
        from_ = int(body.get("from", 0) or 0)
        size = int(body.get("size", 10) if body.get("size") is not None else 10)
        k = from_ + size
        total = 0
        max_score = None
        all_hits = []
        shard_count = 0
        failures = []
        for sid, copies in sorted(self.node.routing.get(index, {}).items()):
            started = [c for c in copies if c.state == ShardRoutingState.STARTED]
            # adaptive replica selection orders copies; failover walks the
            # ranked list
            started = self.response_collector.order_copies(started)
            shard_count += 1
            resp = None
            last_error = None
            for copy in started:
                try:
                    payload = {"index": index, "shard": sid, "body": body,
                               "k": max(k, 1)}
                    if hop_headers:
                        payload["headers"] = hop_headers
                    resp = self._timed_request(copy.node_id, ACTION_QUERY,
                                               payload)
                    break
                except NodeNotConnectedException:
                    continue
                except Exception as e:  # noqa: BLE001 — shard-level failure
                    from elasticsearch_tpu.index.index_service import (
                        _is_request_error,
                    )

                    if _is_request_error(e):
                        raise  # 4xx validation: keeps its own status
                    # the remote query phase executed and failed; record
                    # it and try the next copy (the failure may be
                    # copy-local — a corrupt segment on one replica)
                    last_error = e
                    continue
            if resp is None:
                if last_error is not None:
                    failures.append(shard_failure_entry(
                        index, sid, last_error))
                else:
                    failures.append({"shard": sid, "index": index,
                                     "reason": "no available shard copy"})
                continue
            total += resp["total"]
            if resp["max_score"] is not None:
                max_score = (resp["max_score"] if max_score is None
                             else max(max_score, resp["max_score"]))
            all_hits.extend(resp["hits"])
        # NOTE: unlike the single-node path, all-shards-unavailable stays
        # a degraded 200 here — the RED-shard contract (PR 2): a cluster
        # serving through an outage reports the failed shards loudly in
        # _shards rather than erroring reads that might still match docs
        # on recovering copies moments later
        if failures and not allow_partial_results(body):
            raise SearchPhaseExecutionException(
                "query", "Partial shards failure", failures)
        from elasticsearch_tpu.search.service import (
            multi_pass_sort,
            normalize_sort,
        )

        # normalize_sort collapses a lone _score sort to None: that (and
        # no sort at all) ranks by score descending
        spec = (normalize_sort(body.get("sort"))
                if body.get("sort") is not None else None)
        if spec:
            multi_pass_sort(all_hits, spec,
                            lambda h: tuple(h.get("_sort_tuple", ())))
        else:
            all_hits.sort(key=lambda h: -(h.get("_score") or 0.0))
        for h in all_hits:
            h.pop("_sort_tuple", None)
        resp = {
            "took": 0,
            "timed_out": False,
            "_shards": {"total": shard_count,
                        "successful": shard_count - len(failures),
                        "failed": len(failures)},
            "hits": {
                "total": total,
                "max_score": max_score,
                "hits": all_hits[from_: from_ + size],
            },
        }
        if failures:
            resp["_shards"]["failures"] = failures
        return resp
