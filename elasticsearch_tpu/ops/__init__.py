"""TPU compute kernels.

64-bit note: doc-value columns (dates = epoch millis, longs) need int64/
float64 precision, so the engine enables jax x64 globally. The scoring hot
path stays explicitly float32/bfloat16 — x64 only changes *defaults*, and
all kernels here pin their dtypes.
"""

import jax

jax.config.update("jax_enable_x64", True)
