"""Filter evaluation as dense boolean masks over doc-value columns.

Replaces the reference's filter clauses / Lucene filter iterators
(bool filter context, range queries via BKD trees, exists via
``_field_names``) with vector comparisons + scatter-or over the columnar
CSR doc values (segment.NumericColumn / OrdinalColumn). A filter never
touches postings; it is a pure doc-value computation, which XLA fuses into
the scoring program.

All masks are ``[nd1] bool`` where nd1 = nd_pad + 1; the sentinel slot
(last) may receive padding writes and is excluded by the live mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@jax.jit
def numeric_range_mask(flat_docs, flat_values, lo, hi, nd1_arr):
    """Docs with ANY value in [lo, hi] (CSR scatter-or).

    nd1_arr: zeros([nd1], bool) template (carries the static shape).
    """
    cond = (flat_values >= lo) & (flat_values <= hi)
    return nd1_arr.at[flat_docs].max(cond)


@jax.jit
def numeric_term_mask(flat_docs, flat_values, value, nd1_arr):
    return nd1_arr.at[flat_docs].max(flat_values == value)


@jax.jit
def numeric_terms_mask(flat_docs, flat_values, values, nd1_arr):
    """Docs with any value in the given set ([K] padded with NaN)."""
    cond = (flat_values[:, None] == values[None, :]).any(axis=1)
    return nd1_arr.at[flat_docs].max(cond)


@jax.jit
def ord_range_mask(flat_docs, flat_ords, lo_ord, hi_ord, nd1_arr):
    """Keyword range as a half-open ordinal interval [lo_ord, hi_ord)."""
    cond = (flat_ords >= lo_ord) & (flat_ords < hi_ord)
    return nd1_arr.at[flat_docs].max(cond)


@jax.jit
def ord_terms_mask(flat_docs, flat_ords, ords, nd1_arr):
    """Docs with any ordinal in the set ([K] int32 padded with -1)."""
    cond = (flat_ords[:, None] == ords[None, :]).any(axis=1)
    return nd1_arr.at[flat_docs].max(cond)


@jax.jit
def geo_bounding_box_mask(flat_docs, lat, lon, top, left, bottom, right, nd1_arr):
    cond = (lat <= top) & (lat >= bottom)
    # handle boxes crossing the antimeridian
    crosses = left > right
    in_lon = jnp.where(crosses, (lon >= left) | (lon <= right),
                       (lon >= left) & (lon <= right))
    return nd1_arr.at[flat_docs].max(cond & in_lon)


_EARTH_RADIUS_M = 6371008.8


@jax.jit
def haversine_distance_m(lat1, lon1, lat2, lon2):
    rl1, rl2 = jnp.radians(lat1), jnp.radians(lat2)
    dlat = rl2 - rl1
    dlon = jnp.radians(lon2 - lon1)
    a = jnp.sin(dlat / 2) ** 2 + jnp.cos(rl1) * jnp.cos(rl2) * jnp.sin(dlon / 2) ** 2
    return 2 * _EARTH_RADIUS_M * jnp.arcsin(jnp.sqrt(a))


@jax.jit
def geo_distance_mask(flat_docs, lat, lon, center_lat, center_lon, radius_m, nd1_arr):
    d = haversine_distance_m(lat, lon, center_lat, center_lon)
    return nd1_arr.at[flat_docs].max(d <= radius_m)


@functools.partial(jax.jit, static_argnames=("nd1",))
def docs_mask(doc_indices, nd1: int):
    """Mask from explicit local doc ids (ids query; padded with nd1-1)."""
    return jnp.zeros((nd1,), bool).at[doc_indices].set(True)
