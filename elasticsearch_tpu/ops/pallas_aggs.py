"""Pallas TPU segment-sum kernel for bucketed aggregations.

The reference aggregates doc-at-a-time into per-bucket accumulators via
``LeafBucketCollector.collect(doc, bucket)`` (search/aggregations/
bucket/BucketsAggregator.java); our XLA formulation used
``zeros(n_ords).at[ords].add(v)`` (ops/aggs.py), which TPU lowers to a
serialized scatter loop — the same pathology the scoring kernel removed
(ops/pallas_scoring.py). This kernel computes, in one device pass,

    count[o] = sum_d mask[d] * [ord[d] == o]
    total[o] = sum_d mask[d] * value[d] * [ord[d] == o]

for every bucket ordinal o, as radix-decomposed one-hot matmuls on the
MXU: with hi = ord >> 7, lo = ord & 127,

    acc[hi, lo] += onehot_hi(chunk)^T @ (onehot_lo(chunk) * v)

The grid iterates doc chunks; the (O_SUB, 128) accumulator output block
is revisited across sequential grid steps (constant index_map), so it
lives in VMEM for the whole pass and is flushed to HBM once. count+total
cover terms / histogram / value_count / sum / avg directly and feed the
engine's bucket machinery (search/aggregations.py).

Callers supply per-doc ordinals: terms aggs use the segment's ordinal
column, histograms compute ``(value - offset) // interval`` host- or
device-side first (GlobalOrdinalsStringTermsAggregator /
HistogramAggregator analogs).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from elasticsearch_tpu.index.segment import next_pow2

LANE = 128
# docs per grid step: 8 sublane rows x 128 lanes
CHUNK_SUB = 8
CHUNK = CHUNK_SUB * LANE


def _make_segsum_kernel(o_sub: int, with_sum: bool, with_count: bool):
    def kernel(ord_ref, mask_ref, *refs):
        if with_sum:
            val_ref = refs[0]
            outs = refs[1:]
        else:
            val_ref = None
            outs = refs
        if with_count:
            out_cnt = outs[0]
            out_sum = outs[1] if with_sum else None
        else:
            out_cnt = None
            out_sum = outs[0]
        c = pl.program_id(0)

        ords = ord_ref[...]  # (CHUNK_SUB, LANE) i32
        mask = mask_ref[...] > jnp.float32(0.0)
        valid = mask & (ords >= jnp.int32(0)) \
            & (ords < jnp.int32(o_sub * LANE))
        safe = jnp.where(valid, ords, jnp.int32(0))
        hi = jnp.where(valid, lax.shift_right_logical(
            safe, jnp.int32(7)), jnp.int32(-1))
        lo = jnp.where(valid, jnp.bitwise_and(safe, jnp.int32(LANE - 1)),
                       jnp.int32(-1))
        hi_row = hi.reshape(1, CHUNK)
        lo_row = lo.reshape(1, CHUNK)
        ohT = jnp.where(
            lax.broadcasted_iota(jnp.int32, (o_sub, CHUNK), 0) == hi_row,
            jnp.float32(1.0), jnp.float32(0.0))
        # accT layout (LANE=lo, o_sub=hi): ordinal o sits at
        # [o & 127, o >> 7]
        if with_count:
            lov1 = jnp.where(
                lax.broadcasted_iota(jnp.int32, (LANE, CHUNK), 0) == lo_row,
                jnp.float32(1.0), jnp.float32(0.0))
            cnt = lax.dot_general(lov1, ohT, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)

            @pl.when(c == jnp.int32(0))
            def _():
                out_cnt[...] = cnt

            @pl.when(c != jnp.int32(0))
            def _():
                out_cnt[...] = out_cnt[...] + cnt

        if with_sum:
            vals = val_ref[...]
            # two-pass error-compensated matmul (see pallas_scoring.py):
            # default bf16 MXU passes would round the metric values to 8-bit
            # mantissas; bf16-high + f32-residual summed over two DEFAULT
            # dots restores ~2^-17 rel error at 1/3 of HIGHEST's passes
            # (ohT is 0/1, bf16-exact)
            vrow = vals.reshape(1, CHUNK)
            v_hi = vrow.astype(jnp.bfloat16).astype(jnp.float32)
            v_lo = vrow - v_hi
            lane_iota = lax.broadcasted_iota(jnp.int32, (LANE, CHUNK), 0)
            lov_hi = jnp.where(lane_iota == lo_row, v_hi, jnp.float32(0.0))
            lov_lo = jnp.where(lane_iota == lo_row, v_lo, jnp.float32(0.0))
            tot = (lax.dot_general(lov_hi, ohT, (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32)
                   + lax.dot_general(lov_lo, ohT, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32))

            @pl.when(c == jnp.int32(0))
            def _():
                out_sum[...] = tot

            @pl.when(c != jnp.int32(0))
            def _():
                out_sum[...] = out_sum[...] + tot

    return kernel


@functools.partial(jax.jit, static_argnames=("n_ords", "with_sum",
                                             "with_count", "interpret"))
def segment_aggregate(
    ords,  # [nd] int32 per-doc bucket ordinal (-1 or >= n_ords = skip)
    mask,  # [nd] float32 query-match mask (>0 = in the agg)
    values=None,  # [nd] float32 metric values (with_sum=True)
    *,
    n_ords: int,
    with_sum: bool = False,
    with_count: bool = True,
    interpret: bool = False,
):
    """Per-bucket doc counts (and value sums) in one device pass.

    Returns a tuple of (count [n_ords] f32 if with_count, total [n_ords]
    f32 if with_sum) — sum-only callers set with_count=False to skip the
    count matmul entirely. Inputs of any length are padded to a CHUNK
    multiple internally (mask pads 0, so padding never contributes).

    Accumulation is f32: counts are exact up to 2^24 contributions per
    call (the dispatchers in ops/aggs.py fall back to the int32 scatter
    path beyond that), and sums carry f32 precision.

    Non-finite metric values are sanitized (NaN -> 0, +/-inf -> +/-f32max)
    before the one-hot matmul: a raw inf would turn the 0*inf products of
    every other bucket sharing its lane into NaN. Consequence vs the
    scatter path: an inf value saturates its own bucket's sum instead of
    making it inf exactly, and NaN values are treated as missing.
    """
    assert with_sum or with_count
    nd = ords.shape[0]
    if nd == 0:
        outs = []
        if with_count:
            outs.append(jnp.zeros((n_ords,), jnp.float32))
        if with_sum:
            outs.append(jnp.zeros((n_ords,), jnp.float32))
        return tuple(outs)
    target = ((nd + CHUNK - 1) // CHUNK) * CHUNK
    if target != nd:
        ords = jnp.pad(ords, (0, target - nd))
        mask = jnp.pad(mask, (0, target - nd))
        if values is not None:
            values = jnp.pad(values, (0, target - nd))
    if values is not None:
        # clamp to the bf16-representable range: the kernel's two-pass
        # compensated matmul splits values at bf16 precision, and f32-max
        # would overflow to inf there (inf - inf = NaN poisons buckets)
        fmax = jnp.float32(float(jnp.finfo(jnp.bfloat16).max))
        # clip as well as nan_to_num: finite f32 values above bf16-max would
        # still round to inf inside the kernel's bf16 split
        values = jnp.clip(
            jnp.nan_to_num(values.astype(jnp.float32), nan=0.0,
                           posinf=fmax, neginf=-fmax), -fmax, fmax)
    n_chunks = target // CHUNK
    o_pad = next_pow2(max(n_ords, LANE))
    o_sub = o_pad // LANE

    def zero():
        return jnp.int32(0)

    in_specs = [
        pl.BlockSpec((CHUNK_SUB, LANE), lambda c: (c, zero())),
        pl.BlockSpec((CHUNK_SUB, LANE), lambda c: (c, zero())),
    ]
    operands = [ords.reshape(n_chunks * CHUNK_SUB, LANE),
                mask.reshape(n_chunks * CHUNK_SUB, LANE)]
    if with_sum:
        in_specs.append(pl.BlockSpec((CHUNK_SUB, LANE),
                                     lambda c: (c, zero())))
        operands.append(values.reshape(n_chunks * CHUNK_SUB, LANE))

    # accumulator blocks are revisited every step (constant index map) so
    # they stay resident in VMEM for the whole pass
    n_outs = int(with_count) + int(with_sum)
    out_specs = [pl.BlockSpec((LANE, o_sub), lambda c: (zero(), zero()))
                 for _ in range(n_outs)]
    out_shape = [jax.ShapeDtypeStruct((LANE, o_sub), jnp.float32)
                 for _ in range(n_outs)]

    kwargs = {}
    try:
        params = pltpu.CompilerParams(dimension_semantics=("arbitrary",))
        if not interpret:
            kwargs["compiler_params"] = params
    except (TypeError, AttributeError):
        pass
    out = pl.pallas_call(
        _make_segsum_kernel(o_sub, with_sum, with_count),
        grid=(n_chunks,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=tuple(out_shape),
        interpret=interpret,
        **kwargs,
    )(*operands)

    # accT[lo, hi] -> flat [o_pad] -> [n_ords]
    def unpack(a):
        return a.T.reshape(-1)[:n_ords]

    return tuple(unpack(a) for a in out)


def reference_segment_aggregate(ords, mask, values=None, *, n_ords):
    """Numpy oracle."""
    sel = (mask > 0) & (ords >= 0) & (ords < n_ords)
    cnt = np.zeros(n_ords, np.float32)
    np.add.at(cnt, ords[sel], 1.0)
    if values is None:
        return (cnt,)
    tot = np.zeros(n_ords, np.float32)
    np.add.at(tot, ords[sel], values[sel].astype(np.float32))
    return cnt, tot
