"""BM25 scoring kernels over block-packed postings.

This replaces the reference's per-segment hot loop — Lucene's
``BulkScorer``/BM25 scoring inside ``searcher.search(query, collector)``
(search/query/QueryPhase.java:272) — with one fused XLA program:

    gather posting blocks -> BM25 contributions -> scatter-add dense scores

The dense score accumulator (``[nd_pad + 1]``, sentinel slot last) makes
disjunctions, conjunction counting and filter masking pure vector ops; the
MXU/VPU see large, static-shaped elementwise work instead of branchy
posting iteration. Scoring is *exhaustive* (every posting scored), which on
TPU is faster than WAND-style skipping for all but pathological terms and
guarantees recall@k = 1.0 vs the scalar reference (BASELINE.md gate).

All functions here are shape-polymorphic jit targets; callers bucket
shapes (see search/execute.py) so programs cache across queries.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

# Lucene 7 BM25 defaults (index/similarity/SimilarityService.java — BM25 default)
K1 = 1.2
B = 0.75


def bm25_idf(doc_freq, doc_count):
    """Lucene BM25Similarity.idfExplain: ln(1 + (N - df + 0.5)/(df + 0.5))."""
    import math

    return math.log(1.0 + (doc_count - doc_freq + 0.5) / (doc_freq + 0.5))


@functools.partial(jax.jit, static_argnames=("k1", "b"))
def score_term_blocks(
    block_docs,  # [n_blocks, BLOCK] int32 — segment postings matrix
    block_tfs,  # [n_blocks, BLOCK] float32
    norms,  # [n_norm_fields, nd_pad + 1] float32 — per-field doc lengths
    q_blocks,  # [QB] int32 — indices of this query's posting blocks
    q_weights,  # [QB] float32 — idf * boost per block (0 for padding)
    q_norm_rows,  # [QB] int32 — norm row (field) per block
    q_avgdl,  # [QB] float32 — average field length per block
    q_valid,  # [QB] bool — False for padding lanes (gates match counting)
    k1: float = K1,
    b: float = B,
):
    """Score a weighted disjunction of terms; also count distinct matched
    terms per doc (for operator=and / minimum_should_match).

    Returns (scores [nd1] f32, match_counts [nd1] f32); nd1 = nd_pad + 1,
    the last slot collecting all padding writes (discarded by callers).
    """
    docs = block_docs[q_blocks]  # [QB, BLOCK]
    tfs = block_tfs[q_blocks]  # [QB, BLOCK]
    nd1 = norms.shape[1]
    # flat 1-D gather — 2-D advanced indexing lowers to a slower general
    # gather on TPU (measured ~1.6x on the whole query program)
    flat_idx = (q_norm_rows[:, None] * nd1 + docs).ravel()
    doc_len = norms.ravel()[flat_idx].reshape(docs.shape)
    denom = tfs + k1 * (1.0 - b + b * doc_len / q_avgdl[:, None])
    contrib = q_weights[:, None] * tfs * (k1 + 1.0) / denom
    matched = (tfs > 0.0) & q_valid[:, None]
    contrib = jnp.where(matched, contrib, 0.0)
    scores = jnp.zeros((nd1,), jnp.float32).at[docs].add(
        contrib, mode="drop", unique_indices=False
    )
    counts = jnp.zeros((nd1,), jnp.float32).at[docs].add(
        matched.astype(jnp.float32), mode="drop"
    )
    return scores, counts


@functools.partial(jax.jit, static_argnames=("k1", "b", "num_fields"))
def score_term_blocks_bm25f(
    block_docs,
    block_tfs,
    norms,
    q_blocks,
    q_weights,
    q_norm_rows,
    q_avgdl,
    q_valid,
    q_field_boosts,  # [QB] f32 — per-field weight for BM25F-style combining
    num_fields: int = 1,
    k1: float = K1,
    b: float = B,
):
    """Multi-field variant: per-field boosts fold into the term weight
    (cross_fields-style combining for multi_match / more_like_this)."""
    return score_term_blocks(
        block_docs, block_tfs, norms, q_blocks,
        q_weights * q_field_boosts, q_norm_rows, q_avgdl, q_valid, k1=k1, b=b,
    )


@jax.jit
def constant_score(matched, boost):
    return jnp.where(matched, boost, 0.0).astype(jnp.float32)


@jax.jit
def combine_should(scores_list, matched_list, min_should_match):
    """Sum scores of matching 'should' clauses; matched when at least
    min_should_match clauses matched (BooleanQuery semantics)."""
    total = jnp.zeros_like(scores_list[0])
    count = jnp.zeros_like(scores_list[0])
    for s, m in zip(scores_list, matched_list):
        total = total + jnp.where(m, s, 0.0)
        count = count + m.astype(jnp.float32)
    return total, count >= min_should_match


def select_topk(scores, matched, live1, k: int):
    """Final selection: mask out non-matching/deleted docs, take top-k by
    score with index tiebreak (ascending doc id, like Lucene's collector).

    Returns (top_scores [k], top_docs [k]); non-matching slots have
    score = -inf.
    """
    masked = jnp.where(matched & live1, scores, -jnp.inf)
    k = min(k, masked.shape[0])
    top_scores, top_docs = lax.top_k(masked, k)
    return top_scores, top_docs


select_topk = functools.partial(jax.jit, static_argnames=("k",))(select_topk)


@functools.partial(jax.jit, static_argnames=("k",))
def select_topk_by_key(sort_keys, matched, live1, k: int):
    """Top-k by an arbitrary sort key (field sort). Keys must already be
    oriented so that larger = better (callers negate for ascending)."""
    masked = jnp.where(matched & live1, sort_keys, -jnp.inf)
    k = min(k, masked.shape[0])
    return lax.top_k(masked, k)


@jax.jit
def count_matches(matched, live1):
    return jnp.sum((matched & live1).astype(jnp.int32))
