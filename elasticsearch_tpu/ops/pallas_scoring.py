"""Pallas TPU kernel for the BM25 scoring hot loop.

This is the TPU-native replacement for the reference's per-segment scoring
loop — Lucene's ``BulkScorer`` driven from ``QueryPhase.execute``
(core/src/main/java/org/elasticsearch/search/query/QueryPhase.java:272) —
and for the XLA scatter-add formulation it previously compiled to here
(ops/scoring.py:score_term_blocks). XLA lowers a scatter-add with
duplicate indices to a serialized per-element loop on TPU, which made the
chip 4x slower than host numpy (BENCH_r03). This kernel removes the
scatter entirely:

- The doc space is partitioned into tiles of ``W`` docs (W = TILE_SUB*128).
  The kernel grid iterates tiles; each grid step owns one dense
  ``[TILE_SUB, 128]`` f32 score accumulator that lives in VMEM/vregs and
  never round-trips through HBM.
- For each query term lane, the blocks of postings that can intersect the
  tile are a *contiguous* run of block rows (postings are doc-sorted within
  a term), located host-side from per-block [min_doc, max_doc] metadata.
  The run's rows are DMA'd by the BlockSpec index_map from scalar-prefetched
  per-(tile, lane) row bounds — the DMA engine does the gather.
- The scatter "score[doc] += w*frac" becomes a radix-decomposed one-hot
  matmul on the MXU: with local = doc - tile_base, hi = local >> 7,
  lo = local & 127,

      acc[hi, lo] += sum_p [hi_p == hi] * ([lo_p == lo] * w * frac_p)
                   = onehot_hi^T  @  (onehot_lo * w * frac)

  i.e. one (TILE_SUB x R) @ (R x 128) f32 matmul per lane per tile. The
  one-hot generation is O(R * (TILE_SUB + 128)) VPU compares instead of the
  O(R * W) of a direct dense compare — the scatter itself rides the MXU.
- Per-posting BM25 norm factors ``frac = tf*(k1+1)/(tf + k1*(1-b+b*len/avgdl))``
  are precomputed per segment at staging time (Lucene's analog: norms are
  baked into per-doc impacts), so the kernel needs no random doc-length
  gather; a term's score is just ``idf_weight * frac``.
- The top-k is fused: each tile emits its local top-K (scores, doc ids) and
  its live-match count; the host program merges n_tiles*K candidates with
  one tiny ``lax.top_k``. The dense score vector never reaches HBM in the
  top-k variant. A dense variant writes the [nd] scores (and match counts)
  for plan programs that need downstream masking/aggregation.

All shapes are static and bucketed (T_pad lanes, CB covering-blocks, W)
so compiled programs cache across queries (SURVEY.md section 7.3).
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from elasticsearch_tpu.index.segment import next_pow2
from elasticsearch_tpu.ops.scoring import B, K1

LANE = 128
# default tile = 16384 docs = 128 sublanes x 128 lanes. Measured on a v5e
# (1M-doc corpus, 4-lane query): per-grid-step fixed cost (~4us + ~2us/lane,
# DMA issue latency) dominates the kernel, so fewer/bigger tiles win: 64
# tiles at sub=128/cb=32 runs ~1.0ms/query vs ~1.8ms at sub=64/cb=16 and
# ~3.2ms at sub=32/cb=8 (same covering-window density).
DEFAULT_TILE_SUB = 128
# segment block arrays are padded with this many sentinel rows so that both
# CB-aligned DMA windows (2*cb rows from the aligned start) stay in bounds
# for any window starting at a real block row; cb <= CB_MAX // 2
CB_MAX = 128

NEG_INF = float("-inf")


def tiles_per_step_default() -> int:
    """Grid-coarsening factor for DMA double-buffering across tiles.

    Sourced from ES_TPU_PALLAS_TPS (the registered node setting
    ``search.pallas.tiles_per_step`` exports it at startup). 1 = one tile
    per grid step (historical behavior); 2/4/8 fold that many tiles into
    one step so their posting-window DMAs overlap compute and the fixed
    per-step dispatch cost amortizes."""
    import os

    try:
        v = int(os.environ.get("ES_TPU_PALLAS_TPS", "1"))
    except ValueError:
        return 1
    return v if v in (1, 2, 4, 8) else 1


# ----------------------------------------------------------------------
# Packed postings codec (ISSUE 6: break the bandwidth wall)
#
# The raw layout streams 8 bytes/posting (doc i32 + frac f32) out of HBM
# for every covering window — BENCH_r05 measured the kernel bandwidth-
# bound on exactly that traffic. The packed codec bit-packs each posting
# into ONE i32 word:
#
#     word = (doc << PACK_FRAC_BITS) | frac_q        (frac_q in [1, 4095])
#
# and the kernel unpacks it in VMEM with one logical shift + one mask +
# one i32->f32 convert before the existing two-pass scoring — half the
# posting bytes per query (the Lucene analog: the FOR/bit-packed postings
# codec of index/codec, SURVEY §2.3/§6, inverted for lane-parallel
# decode). frac quantizes linearly over (0, K1+1) — BM25's frac =
# tf(k1+1)/(tf + k1*norm) is strictly below k1+1 for any tf/norm, so the
# scale is a static constant and no per-segment metadata rides along.
# frac_q == 0 is the invalid/padding marker (exactly the frac > 0.0 rule
# the raw kernel keys on), so real postings clamp to frac_q >= 1.
#
# Lossiness: |dequant(q) - frac| <= PACK_FRAC_SCALE/2 (~2.7e-4 absolute,
# ~16x tighter than the bf16 rounding the two-pass compensation exists
# for). Whether that reorders near-tied top-10 ranks is corpus-dependent,
# which is why the codec is settings-gated (raw default) and bench gates
# every packed config on measured recall@10 == 1.0 vs the RAW oracle.
# ----------------------------------------------------------------------

PACK_FRAC_BITS = 12
PACK_FRAC_MASK = (1 << PACK_FRAC_BITS) - 1
PACK_MAX_FRAC = float(K1) + 1.0  # strict upper bound of BM25 frac
PACK_FRAC_SCALE = PACK_MAX_FRAC / PACK_FRAC_MASK
# doc ids must fit the remaining bits (sentinels store doc 0 + frac_q 0)
PACKED_DOC_CAP = 1 << (32 - PACK_FRAC_BITS)


def packed_codec_ok(nd_pad: int) -> bool:
    """The packed word holds 32 - PACK_FRAC_BITS doc bits: real doc ids
    are < nd_pad, so any nd_pad <= 2^20 fits (the 1M bench corpus is
    exactly the boundary); larger doc spaces stay on the raw codec."""
    return nd_pad <= PACKED_DOC_CAP


def quantize_frac(frac: np.ndarray) -> np.ndarray:
    """frac f32 -> 12-bit code; 0 stays 0 (invalid marker), real postings
    clamp to [1, PACK_FRAC_MASK] so frac > 0 survives the round trip."""
    q = np.rint(frac / np.float32(PACK_FRAC_SCALE)).astype(np.int64)
    q = np.clip(q, 1, PACK_FRAC_MASK)
    return np.where(frac > 0.0, q, 0).astype(np.int32)


def dequantize_frac(q: np.ndarray) -> np.ndarray:
    """The exact f32 values the kernel's in-VMEM decode produces (the
    oracle for packed-parity tests)."""
    return (q.astype(np.float32) * np.float32(PACK_FRAC_SCALE)).astype(
        np.float32)


def pack_segment_blocks(block_docs: np.ndarray, block_frac: np.ndarray,
                        sentinel: int,
                        q: Optional[np.ndarray] = None) -> np.ndarray:
    """Bit-pack (docs, frac) into one padded i32 word array — the packed
    analog of pad_segment_blocks (CB_MAX all-zero sentinel rows keep the
    double-window DMA in bounds; word 0 decodes to frac 0 = invalid).
    ``q``: precomputed quantize_frac(block_frac), for callers that also
    need the codes (block-max bounds) — quantization is a full-corpus
    pass and should run once per staging."""
    if not packed_codec_ok(int(sentinel)):
        raise ValueError(
            f"doc space {sentinel} exceeds the packed codec's "
            f"{32 - PACK_FRAC_BITS}-bit doc capacity")
    if q is None:
        q = quantize_frac(block_frac.astype(np.float32))
    docs = np.where(q > 0, block_docs, 0).astype(np.int64)
    words = ((docs.astype(np.uint32) << PACK_FRAC_BITS)
             | q.astype(np.uint32)).view(np.int32)
    pad = np.zeros((CB_MAX, LANE), dtype=np.int32)
    return np.concatenate([words, pad])


def resolve_postings_codec(pref, nd_pad: int) -> str:
    """Effective codec for a segment staging: the explicit preference
    (index setting / caller), else the node-wide default exported via
    ES_TPU_PALLAS_CODEC (search.pallas.postings_codec), demoted to raw
    when the doc space exceeds the packed word's doc capacity."""
    import os

    codec = pref
    if codec in (None, "default"):
        codec = os.environ.get("ES_TPU_PALLAS_CODEC", "raw")
    if codec not in ("raw", "packed"):
        codec = "raw"
    if codec == "packed" and not packed_codec_ok(nd_pad):
        codec = "raw"
    return codec


# ----------------------------------------------------------------------
# Host-side geometry: which docs does tile t get from term lane j?
# ----------------------------------------------------------------------


class TileGeometry(NamedTuple):
    """Static tiling of one segment's doc space."""

    nd_pad: int  # padded doc count (power of two)
    tile_sub: int  # sublanes per tile
    n_tiles: int

    @property
    def tile_w(self) -> int:
        return self.tile_sub * LANE


def tile_geometry(nd_pad: int, tile_sub: int = DEFAULT_TILE_SUB) -> TileGeometry:
    """Pick the tile shape for a segment: W = tile_sub*128 docs per tile,
    shrinking for small segments so n_tiles >= 1 and W <= nd_pad. The doc
    space is floored at one LANE (128): segments smaller than that are
    scored over a 128-doc space whose tail is dead (live mask zeros)."""
    nd_pad = max(nd_pad, LANE)
    if nd_pad & (nd_pad - 1) or tile_sub & (tile_sub - 1):
        raise ValueError(
            f"nd_pad={nd_pad} and tile_sub={tile_sub} must be powers of two "
            f"(otherwise tail docs would fall outside every tile)")
    w = tile_sub * LANE
    while w > nd_pad and w > LANE:
        w //= 2
    sub = w // LANE
    n_tiles = max(nd_pad // w, 1)
    assert n_tiles * sub * LANE == nd_pad
    return TileGeometry(nd_pad=nd_pad, tile_sub=sub, n_tiles=n_tiles)


def pad_segment_blocks(
    block_docs: np.ndarray, block_frac: np.ndarray, sentinel: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Append CB_MAX sentinel rows so CB-aligned DMA windows never read out
    of bounds (sentinel docs fail every tile's range check)."""
    pad_docs = np.full((CB_MAX, LANE), sentinel, dtype=np.int32)
    pad_frac = np.zeros((CB_MAX, LANE), dtype=np.float32)
    return (
        np.concatenate([block_docs.astype(np.int32), pad_docs]),
        np.concatenate([block_frac.astype(np.float32), pad_frac]),
    )


def compute_block_frac(
    block_docs: np.ndarray,
    block_tfs: np.ndarray,
    doc_len: np.ndarray,  # [>= nd_pad (+1)] float32 per-doc field length
    avgdl: float,
    k1: float = K1,
    b: float = B,
) -> np.ndarray:
    """Per-posting BM25 norm factor (everything except idf*boost):
    tf*(k1+1) / (tf + k1*(1-b+b*len/avgdl)). Sentinel/padding lanes
    (tf == 0) get exactly 0, which downstream masks key on."""
    tf = block_tfs.astype(np.float32)
    dl = doc_len[np.minimum(block_docs, len(doc_len) - 1)].astype(np.float32)
    denom = tf + k1 * (1.0 - b + b * dl / max(avgdl, 1e-9))
    with np.errstate(divide="ignore", invalid="ignore"):
        frac = np.where(tf > 0.0, tf * (k1 + 1.0) / denom, 0.0)
    return frac.astype(np.float32)


def block_min_max(block_docs: np.ndarray, block_tfs: np.ndarray,
                  sentinel: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-block [min_doc, max_doc] over *real* postings (tf > 0).

    A term's block run must have NO all-padding block before its last block
    (SegmentBuilder.seal packs postings densely, so this always holds) —
    an empty mid-run block would get bmax=-1/bmin=sentinel and break the
    sortedness that build_tile_tables' searchsorted coverage relies on;
    build_tile_tables guards this with an explicit monotonicity check."""
    real = block_tfs > 0.0
    bmin = np.where(real, block_docs, sentinel).min(axis=1).astype(np.int64)
    bmax = np.where(real, block_docs, -1).max(axis=1).astype(np.int64)
    return bmin, bmax


class QueryLane(NamedTuple):
    """One scoring lane: a term (or term+field) posting run and its weight."""

    block_start: int  # first block row of the term in the segment
    block_count: int
    weight: float  # idf * boost (0 disables the lane)


def build_tile_tables(
    lanes: Sequence[QueryLane],
    bmin: np.ndarray,
    bmax: np.ndarray,
    geom: TileGeometry,
    t_pad: Optional[int] = None,
    cb: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Host-side query planning: per (tile, lane) the absolute block-row
    window [row_lo, row_hi) covering the tile's doc range, padded to
    T_pad lanes. Returns (row_lo, row_hi [n_tiles, T_pad] i32,
    weights [1, T_pad] f32, CB) where CB is the uniform pow2 window bucket.
    The kernel DMAs TWO consecutive CB-aligned windows starting at
    align(row_lo, CB) — rows [align(lo), align(lo) + 2*CB) — so any window
    with row_hi - row_lo <= CB is fully covered regardless of where the
    aligned start lands (it can sit up to CB-1 rows before row_lo)."""
    w = geom.tile_w
    n_tiles = geom.n_tiles
    t_pad = t_pad or next_pow2(max(len(lanes), 1))
    row_lo = np.zeros((n_tiles, t_pad), dtype=np.int32)
    row_hi = np.zeros((n_tiles, t_pad), dtype=np.int32)
    weights = np.zeros((1, t_pad), dtype=np.float32)
    tile_lo = np.arange(n_tiles, dtype=np.int64) * w
    need = 1
    for j, lane in enumerate(lanes):
        s, c = lane.block_start, lane.block_count
        if c <= 0 or lane.weight == 0.0:
            continue
        tb_min = bmin[s: s + c]
        tb_max = bmax[s: s + c]
        if c > 1 and (np.any(np.diff(tb_min) < 0)
                      or np.any(np.diff(tb_max) < 0)):
            raise ValueError(
                f"lane {j}: per-block doc ranges not sorted (empty mid-run "
                f"block or unsorted postings) — coverage would be silently "
                f"wrong")
        # first block whose max_doc >= tile start; first block whose
        # min_doc >= tile end — [first, end) covers the tile
        first = np.searchsorted(tb_max, tile_lo, side="left")
        end = np.searchsorted(tb_min, tile_lo + w, side="left")
        end = np.maximum(end, first)
        row_lo[:, j] = s + first
        row_hi[:, j] = s + end
        weights[0, j] = lane.weight
        cov = int((end - first).max()) if c else 0
        need = max(need, cov)
    # mosaic requires sublane block sizes divisible by 8; the double-window
    # scheme covers any alignment as long as cov <= cb, and the segment
    # padding (CB_MAX rows) must fit both windows: cb <= CB_MAX // 2
    cb_req = next_pow2(max(need, 8))
    if cb_req > CB_MAX // 2:
        raise ValueError(
            f"per-tile covering window of {need} blocks exceeds the kernel "
            f"bound {CB_MAX // 2}; use a smaller tile_sub")
    if cb is not None:
        if cb < cb_req:
            raise ValueError(f"cb={cb} too small, need {cb_req}")
        if cb > CB_MAX // 2 or cb & (cb - 1):
            raise ValueError(
                f"cb={cb} must be a power of two <= {CB_MAX // 2} (the "
                f"second DMA window must stay inside the sentinel padding)")
        cb_req = cb
    return row_lo, row_hi, weights, cb_req


def union_query_lanes(
    lane_sets: Sequence[Sequence[QueryLane]],
) -> Tuple[List[QueryLane], np.ndarray]:
    """Merge Q per-query lane sets into one union lane set plus a
    per-query weight matrix — the host half of cross-query micro-batching
    (ISSUE 5): the union's DMA windows are fetched ONCE per tile and a
    query participates in lane j iff weights[q, j] > 0 (its live-lane
    mask), so a short query in the batch never scores another query's
    terms. Lanes are keyed by their posting run (block_start, block_count)
    — two queries naming the same term share one lane, which is where the
    bandwidth amortization comes from under zipfian traffic."""
    union: List[QueryLane] = []
    index: dict = {}
    rows: List[dict] = []
    for lanes in lane_sets:
        row: dict = {}
        for lane in lanes:
            if lane.block_count <= 0 or lane.weight <= 0.0:
                continue
            key = (lane.block_start, lane.block_count)
            j = index.get(key)
            if j is None:
                j = len(union)
                index[key] = j
                # build coverage with weight 1.0: the union lane is live
                # whenever ANY member uses it
                union.append(QueryLane(lane.block_start, lane.block_count,
                                       1.0))
            row[j] = row.get(j, 0.0) + float(lane.weight)
        rows.append(row)
    t_pad = next_pow2(max(len(union), 1))
    weights = np.zeros((len(lane_sets), t_pad), dtype=np.float32)
    for q, row in enumerate(rows):
        for j, w in row.items():
            weights[q, j] = w
    return union, weights


def build_tile_tables_batched(
    lane_sets: Sequence[Sequence[QueryLane]],
    bmin: np.ndarray,
    bmax: np.ndarray,
    geom: TileGeometry,
    t_pad: Optional[int] = None,
    cb: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Batched form of build_tile_tables: one shared (row_lo, row_hi)
    covering the UNION of Q queries' term lanes plus a [Q, t_pad] weight
    matrix (zero = lane dead for that query). Same geometry-ladder
    contract as the single-query form: raises ValueError when the union's
    covering window exceeds the kernel bound at this tile size."""
    union, weights = union_query_lanes(lane_sets)
    t_pad = max(t_pad or 0, weights.shape[1])
    row_lo, row_hi, _w1, cb_req = build_tile_tables(
        union, bmin, bmax, geom, t_pad=t_pad, cb=cb)
    if weights.shape[1] < t_pad:
        weights = np.concatenate(
            [weights,
             np.zeros((weights.shape[0], t_pad - weights.shape[1]),
                      np.float32)], axis=1)
    return row_lo, row_hi, weights, cb_req


# ----------------------------------------------------------------------
# Block-max pruning (ISSUE 6): per-(tile, lane) upper-bound impacts
# ----------------------------------------------------------------------


def block_frac_max(block_frac: np.ndarray) -> np.ndarray:
    """Per-block max posting impact factor [n_blocks] f32 — the block-max
    metadata of WAND/MaxScore (SURVEY §6), computed at table-build time.

    The max is taken over ALL real postings regardless of the live mask:
    deletes mutate ``Segment.live`` in place after staging, and a bound
    that ignored a since-deleted doc could undercount — keeping tombstoned
    postings in the bound is conservative (a too-high bound only scores a
    tile it could have skipped, never skips one it needed).

    For the packed codec pass the DEQUANTIZED frac (dequantize_frac of
    quantize_frac): rounding can lift a posting up to half a step ABOVE
    its raw value, and the bound must dominate what the kernel actually
    decodes."""
    return block_frac.max(axis=1).astype(np.float32)


def tile_lane_ub(row_lo: np.ndarray, row_hi: np.ndarray,
                 bfmax: np.ndarray) -> np.ndarray:
    """Per-(tile, lane) upper-bound frac over the tile's covering block
    window [row_lo, row_hi) — a superset of the tile's real postings, so
    max over it upper-bounds any in-tile posting's frac. [n_tiles, t_pad]
    f32 (0 for empty windows / dead lanes).

    Vectorized (this runs per slot per pruned query): windows are short
    (<= the covering bucket), so a padded gather over [n_tiles,
    max_window] per lane beats per-window Python slicing."""
    n_tiles, t_pad = row_lo.shape
    out = np.zeros((n_tiles, t_pad), np.float32)
    n_blocks = len(bfmax)
    for j in range(t_pad):
        lo = row_lo[:, j].astype(np.int64)
        hi = row_hi[:, j].astype(np.int64)
        wmax = int((hi - lo).max()) if n_tiles else 0
        if wmax <= 0:
            continue
        idx = lo[:, None] + np.arange(wmax)[None, :]
        valid = idx < hi[:, None]
        vals = np.where(valid,
                        bfmax[np.minimum(idx, n_blocks - 1)], 0.0)
        out[:, j] = vals.max(axis=1)
    return out


def plan_pruned_tiles(row_lo: np.ndarray, row_hi: np.ndarray,
                      weights: np.ndarray, bfmax: np.ndarray,
                      probe_tiles: int = 8,
                      ub: Optional[np.ndarray] = None) -> Optional[dict]:
    """Host half of block-max pruned scoring: order tiles by their summed
    block-max score bound and split them into a PROBE set (scored
    unconditionally, seeds the running top-k threshold) and a REST set
    (scored only if its bound can still beat the threshold — decided
    on-device, see score_tiles_pruned). Returns None when the tile count
    is too small to prune (callers run the exhaustive kernel).

    ``weights`` is the [Q, t_pad] per-query weight matrix (a single query
    passes its [1, t_pad] row); bounds[t, q] = sum_j w[q, j] * ub[t, j]
    upper-bounds ANY doc's score for query q within tile t — the
    tile-granular WAND invariant the pruning tests property-check.

    ``ub`` lets callers supply precomputed (cached) per-(tile, lane)
    bounds — a lane's column depends only on (segment, geometry, posting
    run), so it is invariant across queries naming the same term
    (MeshPlanExecutor.tile_lane_ub_cached)."""
    n_tiles = row_lo.shape[0]
    probe = max(1, min(int(probe_tiles), n_tiles))
    if n_tiles - probe <= 0:
        return None
    if ub is None:
        ub = tile_lane_ub(row_lo, row_hi, bfmax)
    bounds = (ub @ weights.T).astype(np.float32)  # [n_tiles, Q]
    order = np.argsort(-bounds.max(axis=1), kind="stable").astype(np.int32)
    sel_p, sel_r = order[:probe], order[probe:]
    return {
        "tid_probe": sel_p,
        "rl_probe": np.ascontiguousarray(row_lo[sel_p]),
        "rh_probe": np.ascontiguousarray(row_hi[sel_p]),
        "tid_rest": sel_r,
        "rl_rest": np.ascontiguousarray(row_lo[sel_r]),
        "rh_rest": np.ascontiguousarray(row_hi[sel_r]),
        "bounds_rest": np.ascontiguousarray(bounds[sel_r]),
        "n_tiles": n_tiles,
    }


# ----------------------------------------------------------------------
# The kernel
# ----------------------------------------------------------------------


def _make_kernel(t_pad: int, cb: int, sub: int, k: int, dense: bool,
                 with_counts: bool, tps: int = 1, q_batch: int = 1,
                 codec: str = "raw", with_sel: bool = False):
    """Kernel body. Mosaic constraints shape the formulation:

    - only lane-collapsing reshapes ((cb,128) -> (1, cb*128)) lower; the
      column reshape (-> (rows, 1)) crashes the backend compiler, so every
      per-posting vector lives as a (1, rows) row and the accumulator is
      kept TRANSPOSED: accT[lane, sub] with doc local id = sub*128 + lane.
    - the scatter-matmul contracts over the posting axis on the LANES of
      both operands (the q @ k^T pattern):
          accT (LANE, sub) += lovT (LANE, rows) . ohT (sub, rows)^T
      where ohT one-hots the doc's high radix (local >> 7) and lovT
      one-hots the low radix (local & 127) scaled by weight*frac.
    - scalar stores to VMEM are rejected, so the per-tile top-k builds
      (1, k) vectors with masked selects and stores whole blocks.
    - bool -> f32 astype trips a recursive convert_element_type fallback;
      where-selects lower cleanly.

    ``tps`` (tiles per grid step): grid coarsening for DMA double-buffering
    across tiles — one grid step owns tps consecutive tiles, so all of the
    step's posting windows are issued up front and the DMA engine streams
    tile i+1's windows while the MXU works tile i, and the fixed per-step
    dispatch cost (which dominates the kernel — see module docstring) is
    paid once per tps tiles.

    ``q_batch`` (cross-query micro-batching, ISSUE 5): the tables cover
    the UNION of Q concurrent queries' term lanes and ``weights`` is
    [Q, t_pad]. The per-(tile, lane) posting windows are DMA'd ONCE and
    the lane's weight-free contribution matrix (one one-hot build + MXU
    matmul pair) is computed ONCE; each query then folds it in with a
    single f32 scale-add against its own weight — zero weight is the
    per-query live-lane mask, so a query never scores lanes it didn't
    ask for (and its match COUNTS only count its own lanes). Per-query
    state is a [Q*LANE, sub] scratch accumulator, and the top-k variant
    emits per-query candidate rows. q_batch == 1 keeps the historical
    single-query formulation bit-for-bit (weights folded into the
    one-hot before the matmul), so the unbatched path is untouched.

    ``codec`` (bit-packed postings, ISSUE 6): "packed" DMAs ONE i32 word
    per posting — (doc << PACK_FRAC_BITS) | frac_q — and decodes it in
    VMEM with a logical shift + mask + i32->f32 convert before the
    unchanged two-pass scoring, halving the posting-window HBM traffic
    the kernel is bound on. "raw" keeps the historical (docs, frac) pair
    layout untouched.

    ``with_sel`` (block-max pruned scoring, ISSUE 6): the grid runs over
    an arbitrary SUBSET of tiles named by a third scalar-prefetch array
    ``tile_ids`` (row tables arrive pre-gathered in subset order). A
    subset row whose windows are all empty (row_lo == row_hi == 0 — how
    the pruned orchestration marks a skipped tile at runtime) writes
    empty candidate rows without paying the top-k extraction, and its
    window DMAs collapse onto block 0 (consecutive identical block
    indices are not re-fetched by the pipeline), so a pruned tile costs
    neither bandwidth nor MXU work.
    """
    w = sub * LANE
    # two consecutive cb-aligned DMA windows per lane; each processes its
    # cb rows independently so its whole compute block can be skipped
    rows = cb * LANE
    packed = codec == "packed"
    stride = 2 if packed else 4

    def kernel(*all_refs):
        if with_sel:
            rowlo_ref, rowhi_ref, tid_ref = all_refs[:3]
            refs = all_refs[3:]
        else:
            rowlo_ref, rowhi_ref = all_refs[:2]
            refs = all_refs[2:]

        def dref(j, ti, half):
            return refs[stride * (j * tps + ti) + 2 * half]

        def fref(j, ti, half):
            return refs[stride * (j * tps + ti) + 2 * half + 1]

        def pref(j, ti, half):
            return refs[stride * (j * tps + ti) + half]

        base_in = stride * t_pad * tps
        n_live = tps if with_sel else 1
        live_refs = refs[base_in: base_in + n_live]
        w_ref = refs[base_in + n_live]
        n_outs = (1 + int(with_counts)) if dense else 3
        outs = refs[base_in + n_live + 1: base_in + n_live + 1 + n_outs]
        acc_ref = refs[base_in + n_live + 1 + n_outs]
        cnt_ref = (refs[base_in + n_live + 2 + n_outs]
                   if with_counts else None)
        t = pl.program_id(0)

        def tile_topk(accT, live, base):
            """Per-(tile, query) fused top-k extraction (the historical
            inline form, factored so the sel-mode branch shares it)."""
            matched = (accT > jnp.float32(0.0)) & live
            hits = jnp.sum(jnp.where(matched, jnp.float32(1.0),
                                     jnp.float32(0.0)))
            # float literals must be explicit f32: a weak python -inf
            # traces as an f64 scalar inside the kernel and crashes the
            # TPU compiler
            ninf = jnp.float32(NEG_INF)
            masked = jnp.where(matched, accT, ninf)
            # local doc id at accT[lane, s] is s*128 + lane
            lin = (lax.broadcasted_iota(jnp.int32, (LANE, sub), 1)
                   * jnp.int32(LANE)
                   + lax.broadcasted_iota(jnp.int32, (LANE, sub), 0))
            outv_s = jnp.full((1, k), NEG_INF, jnp.float32)
            outv_d = jnp.full((1, k), -1, jnp.int32)
            k_iota = lax.broadcasted_iota(jnp.int32, (1, k), 1)
            for i in range(k):
                mx = jnp.max(masked)
                sel = jnp.where(masked == mx, lin, jnp.int32(w))
                idx = jnp.min(sel)
                outv_s = jnp.where(k_iota == jnp.int32(i), mx, outv_s)
                outv_d = jnp.where(
                    k_iota == jnp.int32(i),
                    jnp.where(mx == ninf, jnp.int32(-1), base + idx),
                    outv_d)
                masked = jnp.where(lin == idx, ninf, masked)
            return hits, outv_s, outv_d

        for ti in range(tps):
            pos = jnp.int32(t) * jnp.int32(tps) + jnp.int32(ti)
            # with_sel: the grid position indexes the pre-gathered row
            # tables; the REAL tile id (doc base, live-mask row) comes
            # from the prefetched selection array
            tile = tid_ref[pos] if with_sel else pos
            base = tile * jnp.int32(w)
            # scratch accumulators persist across grid steps (and tiles
            # within a step): reset first (rows [q*LANE, (q+1)*LANE) hold
            # query q's transposed tile accumulator)
            acc_ref[...] = jnp.zeros((q_batch * LANE, sub), jnp.float32)
            if with_counts:
                cnt_ref[...] = jnp.zeros((q_batch * LANE, sub), jnp.float32)
            for j in range(t_pad):
                rlo = rowlo_ref[pos, j]
                rhi = rowhi_ref[pos, j]
                # aligned first row actually DMA'd (mirrors lane_map below)
                sb = lax.div(rlo, jnp.int32(cb)) * jnp.int32(cb)
                wj = w_ref[0, j]
                for half in (0, 1):
                    start = sb + jnp.int32(half * cb)
                    # skip the whole window when it can't intersect the
                    # lane's covering run: empty lanes skip both halves,
                    # and the second half only runs on the rare misaligned
                    # overflow — this halves the one-hot/MXU work in the
                    # common case
                    needed = (rhi > rlo) & (start < rhi) \
                        & (start + jnp.int32(cb) > rlo)

                    @pl.when(needed)
                    def _(j=j, ti=ti, half=half, start=start, rlo=rlo,
                          rhi=rhi, wj=wj, base=base):
                        if packed:
                            # in-VMEM decode: one logical shift + one mask
                            # + one i32->f32 convert per window — the DMA
                            # streamed HALF the bytes of the raw layout.
                            # shift_right_logical: doc 20 bits + frac 12
                            # bits fills the word, so the sign bit can be
                            # set and an arithmetic shift would smear it
                            word = pref(j, ti, half)[...]
                            docs = lax.shift_right_logical(
                                word, jnp.int32(PACK_FRAC_BITS))
                            fq = jnp.bitwise_and(
                                word, jnp.int32(PACK_FRAC_MASK))
                            frac = fq.astype(jnp.float32) * jnp.float32(
                                PACK_FRAC_SCALE)
                        else:
                            docs = dref(j, ti, half)[...]
                            frac = fref(j, ti, half)[...]
                        blk = start + lax.broadcasted_iota(
                            jnp.int32, (cb, LANE), 0)
                        local = docs - base
                        valid = (
                            (blk >= rlo) & (blk < rhi)
                            & (local >= jnp.int32(0)) & (local < jnp.int32(w))
                            & (frac > jnp.float32(0.0))
                        )
                        # NB every scalar int literal below must be an
                        # explicit int32: inside the kernel trace weak
                        # python ints become i64 scalars, and mosaic's
                        # i64->i32 demotion fallback recurses forever
                        safe = jnp.where(valid, local, jnp.int32(0))
                        hi = jnp.where(valid, lax.shift_right_logical(
                            safe, jnp.int32(7)), jnp.int32(-1))
                        lo = jnp.where(valid, jnp.bitwise_and(
                            safe, jnp.int32(LANE - 1)), jnp.int32(-1))
                        hi_row = hi.reshape(1, rows)
                        lo_row = lo.reshape(1, rows)
                        wf_row = ((frac * wj).reshape(1, rows)
                                  if q_batch == 1 else None)
                        ohT = jnp.where(
                            lax.broadcasted_iota(
                                jnp.int32, (sub, rows), 0) == hi_row,
                            jnp.float32(1.0), jnp.float32(0.0))
                        # two-pass error-compensated matmul: the MXU's
                        # default single bf16 pass rounds w*frac to an
                        # 8-bit mantissa (~0.2% rel error — enough to
                        # reorder near-tied BM25 ranks vs the host oracle),
                        # and Precision.HIGHEST costs 6 passes. bf16-high +
                        # f32-residual summed over two DEFAULT dots gives
                        # ~2^-17 rel error at 1/3 the passes (ohT is 0/1,
                        # bf16-exact).
                        lane_iota = lax.broadcasted_iota(
                            jnp.int32, (LANE, rows), 0)
                        if q_batch == 1:
                            wf_hi = wf_row.astype(jnp.bfloat16).astype(
                                jnp.float32)
                            wf_lo = wf_row - wf_hi
                            lov_hi = jnp.where(lane_iota == lo_row, wf_hi,
                                               jnp.float32(0.0))
                            lov_lo = jnp.where(lane_iota == lo_row, wf_lo,
                                               jnp.float32(0.0))
                            acc_ref[...] = acc_ref[...] + lax.dot_general(
                                lov_hi, ohT, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) + lax.dot_general(
                                lov_lo, ohT, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
                            if with_counts:
                                lovT1 = jnp.where(lane_iota == lo_row,
                                                  jnp.float32(1.0),
                                                  jnp.float32(0.0))
                                cnt_ref[...] = cnt_ref[...] + lax.dot_general(
                                    lovT1, ohT, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
                        else:
                            # batched: the lane's weight-free contribution
                            # matrix is built ONCE (same two-pass bf16
                            # error compensation, applied to frac alone —
                            # the f32 weight multiplies after the dot, so
                            # precision matches the single-query path);
                            # each query folds it in with one scale-add,
                            # which is how one DMA + one MXU pass serve
                            # the whole batch
                            f_row = frac.reshape(1, rows)
                            f_hi = f_row.astype(jnp.bfloat16).astype(
                                jnp.float32)
                            f_lo = f_row - f_hi
                            lov_hi = jnp.where(lane_iota == lo_row, f_hi,
                                               jnp.float32(0.0))
                            lov_lo = jnp.where(lane_iota == lo_row, f_lo,
                                               jnp.float32(0.0))
                            contrib = lax.dot_general(
                                lov_hi, ohT, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) + lax.dot_general(
                                lov_lo, ohT, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
                            if with_counts:
                                lovT1 = jnp.where(lane_iota == lo_row,
                                                  jnp.float32(1.0),
                                                  jnp.float32(0.0))
                                ccontrib = lax.dot_general(
                                    lovT1, ohT, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
                            for q in range(q_batch):
                                wq = w_ref[q, j]
                                qs = pl.ds(q * LANE, LANE)
                                acc_ref[qs, :] = (acc_ref[qs, :]
                                                  + wq * contrib)
                                if with_counts:
                                    # weight > 0 is the per-query live-
                                    # lane mask: a dead lane must not
                                    # count toward minimum_should_match
                                    cnt_ref[qs, :] = cnt_ref[qs, :] + \
                                        jnp.where(wq > jnp.float32(0.0),
                                                  ccontrib,
                                                  jnp.float32(0.0))
            if with_sel:
                # sel mode serves the fused top-k only. A runtime-skipped
                # tile (all windows empty — the pruned orchestration
                # zeroed its row table) writes empty candidate rows and
                # pays neither the live-mask DMA nor the top-k loop.
                scored = rowhi_ref[pos, 0] > rowlo_ref[pos, 0]
                for j in range(1, t_pad):
                    scored = jnp.logical_or(
                        scored, rowhi_ref[pos, j] > rowlo_ref[pos, j])
                out_s, out_d, out_h = outs

                @pl.when(jnp.logical_not(scored))
                def _(ti=ti):
                    for q in range(q_batch):
                        out_h[pl.ds(ti, 1), pl.ds(q, 1)] = jnp.zeros(
                            (1, 1, 1), jnp.float32)
                        out_s[pl.ds(ti, 1), pl.ds(q, 1)] = jnp.full(
                            (1, 1, k), NEG_INF, jnp.float32)
                        out_d[pl.ds(ti, 1), pl.ds(q, 1)] = jnp.full(
                            (1, 1, k), -1, jnp.int32)

                @pl.when(scored)
                def _(ti=ti, base=base):
                    live = live_refs[ti][...] > jnp.float32(0.0)
                    for q in range(q_batch):
                        accT = (acc_ref[...] if q_batch == 1
                                else acc_ref[pl.ds(q * LANE, LANE), :])
                        hits, outv_s, outv_d = tile_topk(accT, live, base)
                        out_h[pl.ds(ti, 1), pl.ds(q, 1)] = \
                            hits.reshape(1, 1, 1)
                        out_s[pl.ds(ti, 1), pl.ds(q, 1)] = \
                            outv_s.reshape(1, 1, k)
                        out_d[pl.ds(ti, 1), pl.ds(q, 1)] = \
                            outv_d.reshape(1, 1, k)
                continue
            # (LANE, sub) transposed live slab for THIS tile (shared by
            # every query of the batch); tps==1 keeps the historical
            # full-block access pattern
            if tps == 1:
                live = live_refs[0][...] > jnp.float32(0.0)
            else:
                live = live_refs[0][pl.ds(ti * LANE, LANE), :] \
                    > jnp.float32(0.0)
            for q in range(q_batch):
                if q_batch == 1:
                    accT = acc_ref[...]
                    cntT = cnt_ref[...] if with_counts else None
                else:
                    accT = acc_ref[pl.ds(q * LANE, LANE), :]
                    cntT = (cnt_ref[pl.ds(q * LANE, LANE), :]
                            if with_counts else None)
                if dense:
                    sc = jnp.where(live, accT, jnp.float32(0.0))
                    if q_batch == 1:
                        if tps == 1:
                            outs[0][...] = sc
                            if with_counts:
                                outs[1][...] = jnp.where(live, cntT,
                                                         jnp.float32(0.0))
                        else:
                            outs[0][pl.ds(ti * LANE, LANE), :] = sc
                            if with_counts:
                                outs[1][pl.ds(ti * LANE, LANE), :] = jnp.where(
                                    live, cntT, jnp.float32(0.0))
                    else:
                        outs[0][pl.ds(q, 1), pl.ds(ti * LANE, LANE), :] = \
                            sc[None]
                        if with_counts:
                            outs[1][pl.ds(q, 1), pl.ds(ti * LANE, LANE), :] = \
                                jnp.where(live, cntT, jnp.float32(0.0))[None]
                    continue
                out_s, out_d, out_h = outs
                hits, outv_s, outv_d = tile_topk(accT, live, base)
                if q_batch > 1:
                    out_h[pl.ds(ti, 1), pl.ds(q, 1)] = hits.reshape(1, 1, 1)
                    out_s[pl.ds(ti, 1), pl.ds(q, 1)] = outv_s.reshape(1, 1, k)
                    out_d[pl.ds(ti, 1), pl.ds(q, 1)] = outv_d.reshape(1, 1, k)
                elif tps == 1:
                    out_h[...] = hits.reshape(1, 1, 1)
                    out_s[...] = outv_s.reshape(1, 1, k)
                    out_d[...] = outv_d.reshape(1, 1, k)
                else:
                    out_h[pl.ds(ti, 1)] = hits.reshape(1, 1, 1)
                    out_s[pl.ds(ti, 1)] = outv_s.reshape(1, 1, k)
                    out_d[pl.ds(ti, 1)] = outv_d.reshape(1, 1, k)

    return kernel


def _compiler_params():
    try:
        return pltpu.CompilerParams(dimension_semantics=("arbitrary",))
    except (TypeError, AttributeError):  # older/newer API drift
        return None


@functools.partial(
    jax.jit,
    static_argnames=("t_pad", "cb", "sub", "k", "dense", "with_counts",
                     "interpret", "tiles_per_step", "q_batch", "codec"),
)
def score_tiles(
    docs_padded,  # [n_blocks + CB_MAX, LANE] i32 (pad_segment_blocks);
    # codec="packed": the packed word array (pack_segment_blocks)
    frac_padded,  # [n_blocks + CB_MAX, LANE] f32; codec="packed": None
    live_t,  # [n_tiles * LANE, sub] f32 (1.0 = live; build_live_t)
    row_lo,  # [n_tiles, t_pad] i32
    row_hi,  # [n_tiles, t_pad] i32
    weights,  # [q_batch, t_pad] f32 ([1, t_pad] unbatched)
    *,
    t_pad: int,
    cb: int,
    sub: int,
    k: int = 10,
    dense: bool = False,
    with_counts: bool = False,
    interpret: bool = False,
    tiles_per_step: int = 1,
    q_batch: int = 1,
    codec: str = "raw",
    tile_ids=None,  # [n_sel] i32: score ONLY these tiles (row tables
    # pre-gathered in the same order); fused top-k variant only
):
    """Run the tile-scoring kernel over a segment.

    top-k variant (dense=False): returns (tile_scores [n_tiles, q_batch, k]
    f32, tile_docs [n_tiles, q_batch, k] i32 (-1 = empty), tile_hits
    [n_tiles, q_batch, 1]) — q_batch is 1 for a single query, preserving
    the historical shapes.

    dense variant (dense=True): returns scores [n_tiles*LANE, sub] f32 in
    the kernel's transposed tile layout (dense_to_flat -> [nd_pad]) and,
    with_counts, match counts of the same shape (for minimum_should_match
    / conjunction masking). With q_batch > 1 both gain a leading [q_batch]
    axis.

    tiles_per_step > 1 coarsens the grid: each step owns that many
    consecutive tiles, double-buffering their DMA windows against compute
    and amortizing the fixed per-grid-step cost that dominates this kernel
    (the output layouts are unchanged). Clamped down to a divisor of
    n_tiles.

    q_batch > 1 is cross-query micro-batching (ISSUE 5): row_lo/row_hi
    cover the UNION of the batch's term lanes (build_tile_tables_batched)
    and weights carries one row per query (0 = lane dead for that query).
    Corpus bytes stream ONCE per tile for the whole batch; per-query cost
    reduces to one scale-add per live lane plus the per-tile top-k loop.

    codec="packed" streams the bit-packed posting words instead of the
    (docs, frac) pair — HALF the posting bytes — and decodes in-kernel
    (pass the pack_segment_blocks array as docs_padded, frac_padded
    None). tile_ids scores an arbitrary tile SUBSET (block-max pruning,
    ISSUE 6): row_lo/row_hi arrive pre-gathered in subset order, outputs
    have one candidate row per subset entry, and a runtime-zeroed row
    (row_lo == row_hi == 0) is skipped without DMA or compute.
    """
    with_sel = tile_ids is not None
    if with_sel and (dense or with_counts):
        # dense / match-count consumers need every tile's output —
        # pruning's exhaustive-fallback contract lives one level up
        raise ValueError(
            "tile-subset scoring serves the fused top-k variant only")
    n_tiles = row_lo.shape[0]
    w = sub * LANE
    k = min(k, w)
    q_batch = max(1, int(q_batch))
    tps = max(1, int(tiles_per_step))
    while n_tiles % tps:
        tps //= 2

    # index maps must return int32 everywhere (and build the constant INSIDE
    # the lambda — captured tracers are rejected): the engine runs with jax
    # x64 enabled (ops/__init__.py), under which python-int literals become
    # i64 constants in the mosaic transform functions and crash the TPU
    # compile helper
    def zero():
        return jnp.int32(0)

    def lane_map(j, ti, half):
        # lax.div (truncating) == floor-div for the non-negative row indices;
        # jnp's // lowers to a floor_divide jaxpr the mosaic index_map
        # rejects. half=0/1 selects the first/second cb-aligned window of
        # tile t*tps + ti (sel mode: the SUBSET position — tables arrive
        # pre-gathered, so position-indexing is correct there too).
        if with_sel:
            return lambda t, rlo, rhi, tid: (
                lax.div(rlo[jnp.int32(t) * jnp.int32(tps) + jnp.int32(ti),
                            j],
                        jnp.int32(cb)) + jnp.int32(half), zero())
        return lambda t, rlo, rhi: (
            lax.div(rlo[jnp.int32(t) * jnp.int32(tps) + jnp.int32(ti), j],
                    jnp.int32(cb)) + jnp.int32(half), zero())

    in_specs = []
    operands = []
    for j in range(t_pad):
        for ti in range(tps):
            for half in (0, 1):
                in_specs.append(pl.BlockSpec((cb, LANE), lane_map(j, ti, half)))
                operands.append(docs_padded)
                if codec != "packed":
                    in_specs.append(
                        pl.BlockSpec((cb, LANE), lane_map(j, ti, half)))
                    operands.append(frac_padded)
    if with_sel:
        # per-tile live slabs indexed by the REAL tile id from the
        # prefetched selection (a runtime-redirected skipped tile reads
        # row 0 — consecutive identical indices are not re-fetched)
        for ti in range(tps):
            in_specs.append(pl.BlockSpec(
                (LANE, sub),
                (lambda t, rlo, rhi, tid, ti=ti:
                 (tid[jnp.int32(t) * jnp.int32(tps) + jnp.int32(ti)],
                  zero()))))
            operands.append(live_t)
    else:
        in_specs.append(
            pl.BlockSpec((tps * LANE, sub),
                         lambda t, rlo, rhi: (t, zero())))
        operands.append(live_t)
    # the SMEM spec needs an explicit index map: the auto-generated default
    # returns weak python-int zeros, which trace to i64 under x64 and fail
    # mosaic legalization on real hardware (interpret mode doesn't catch it)
    if with_sel:
        smem_map = lambda t, rlo, rhi, tid: (zero(), zero())  # noqa: E731
    else:
        smem_map = lambda t, rlo, rhi: (zero(), zero())  # noqa: E731
    in_specs.append(pl.BlockSpec((q_batch, t_pad), smem_map,
                                 memory_space=pltpu.SMEM))
    operands.append(weights)

    if dense:
        if q_batch == 1:
            out_specs = [
                pl.BlockSpec((tps * LANE, sub),
                             lambda t, rlo, rhi: (t, zero()))]
            out_shape = [
                jax.ShapeDtypeStruct((n_tiles * LANE, sub), jnp.float32)]
            if with_counts:
                out_specs.append(
                    pl.BlockSpec((tps * LANE, sub),
                                 lambda t, rlo, rhi: (t, zero())))
                out_shape.append(
                    jax.ShapeDtypeStruct((n_tiles * LANE, sub), jnp.float32))
        else:
            # per-query dense slabs: the leading q axis rides whole in
            # every block (only the last two dims face mosaic's
            # divisibility-or-full-dim rule, and those are unchanged)
            out_specs = [
                pl.BlockSpec((q_batch, tps * LANE, sub),
                             lambda t, rlo, rhi: (zero(), t, zero()))]
            out_shape = [jax.ShapeDtypeStruct(
                (q_batch, n_tiles * LANE, sub), jnp.float32)]
            if with_counts:
                out_specs.append(
                    pl.BlockSpec((q_batch, tps * LANE, sub),
                                 lambda t, rlo, rhi: (zero(), t, zero())))
                out_shape.append(jax.ShapeDtypeStruct(
                    (q_batch, n_tiles * LANE, sub), jnp.float32))
    else:
        # 3D outputs: the last two dims of each block equal the array dims,
        # satisfying mosaic's (8, 128)-divisibility-or-full-dim rule for
        # small per-tile outputs (the middle dim is the per-query row)
        if with_sel:
            out_map = lambda t, rlo, rhi, tid: (t, zero(), zero())  # noqa: E731
        else:
            out_map = lambda t, rlo, rhi: (t, zero(), zero())  # noqa: E731
        out_specs = [
            pl.BlockSpec((tps, q_batch, k), out_map),
            pl.BlockSpec((tps, q_batch, k), out_map),
            pl.BlockSpec((tps, q_batch, 1), out_map),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((n_tiles, q_batch, k), jnp.float32),
            jax.ShapeDtypeStruct((n_tiles, q_batch, k), jnp.int32),
            jax.ShapeDtypeStruct((n_tiles, q_batch, 1), jnp.float32),
        ]

    scratch_shapes = [pltpu.VMEM((q_batch * LANE, sub), jnp.float32)]
    if with_counts:
        scratch_shapes.append(pltpu.VMEM((q_batch * LANE, sub), jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3 if with_sel else 2,
        grid=(n_tiles // tps,),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=scratch_shapes,
    )
    kernel = _make_kernel(t_pad, cb, sub, k, dense, with_counts, tps,
                          q_batch, codec, with_sel)
    kwargs = {}
    params = _compiler_params()
    if params is not None and not interpret:
        kwargs["compiler_params"] = params
    prefetch = ((row_lo, row_hi, jnp.asarray(tile_ids, jnp.int32))
                if with_sel else (row_lo, row_hi))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=tuple(out_shape),
        interpret=interpret,
        **kwargs,
    )(*prefetch, *operands)
    return out


@functools.partial(jax.jit, static_argnames=("k",))
def merge_tile_topk(tile_scores, tile_docs, tile_hits, k: int):
    """Merge per-tile candidates: global top-k by score (doc id descending
    tiebreak is irrelevant — -1 slots carry -inf) + total live hit count."""
    flat_s = tile_scores.reshape(-1)
    flat_d = tile_docs.reshape(-1)
    kk = min(k, flat_s.shape[0])
    top_s, top_i = lax.top_k(flat_s, kk)
    return top_s, flat_d[top_i], jnp.sum(tile_hits).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("k",))
def merge_tile_topk_batched(tile_scores, tile_docs, tile_hits, k: int):
    """Per-query merge of a batched top-k launch: tile_scores/tile_docs
    are [n_tiles, Q, k]; returns (top_s [Q, k'], top_d [Q, k'],
    hits [Q] i32) with k' = min(k, n_tiles*k)."""
    n_tiles, q, kk_in = tile_scores.shape
    flat_s = tile_scores.transpose(1, 0, 2).reshape(q, -1)
    flat_d = tile_docs.transpose(1, 0, 2).reshape(q, -1)
    kk = min(k, flat_s.shape[1])
    top_s, top_i = lax.top_k(flat_s, kk)
    top_d = jnp.take_along_axis(flat_d, top_i, axis=1)
    hits = jnp.sum(tile_hits.reshape(n_tiles, q), axis=0).astype(jnp.int32)
    return top_s, top_d, hits


@functools.partial(
    jax.jit,
    static_argnames=("t_pad", "cb", "sub", "k", "q_batch", "q_real",
                     "codec", "interpret", "tiles_per_step"),
)
def score_tiles_pruned(
    docs_padded,  # raw: padded docs; packed: the packed word array
    frac_padded,  # raw: padded frac; packed: None
    live_t,
    rl_probe, rh_probe, tid_probe,  # plan_pruned_tiles outputs
    rl_rest, rh_rest, tid_rest,
    bounds_rest,  # [n_rest, q_batch] f32 per-(tile, query) score bounds
    weights,  # [q_batch, t_pad] f32
    *,
    t_pad: int,
    cb: int,
    sub: int,
    k: int = 10,
    q_batch: int = 1,
    q_real: Optional[int] = None,
    codec: str = "raw",
    interpret: bool = False,
    tiles_per_step: int = 1,
):
    """Block-max pruned top-k scoring (ISSUE 6) — ONE compiled program,
    no host round-trip (the bench backend pays a fixed ~70 ms per D2H
    sync, so a host-side threshold exchange would drown the win):

    1. PROBE pass: score the ``probe`` highest-bound tiles (host-ordered
       by plan_pruned_tiles) and merge their candidates — the k-th best
       score per query is the running threshold theta_q (a lower bound on
       the FINAL k-th score, since the candidate pool only grows).
    2. In-program gate: a rest tile survives iff ANY real member's bound
       can still beat its threshold (bounds[t, q] >= theta_q — per-query
       thresholds over the union lanes, so batching composes without
       cross-member leakage). Non-survivors get their row-table windows
       ZEROED at runtime: the sel-mode kernel then skips their compute
       and their window DMAs collapse onto block 0 (scalar-prefetch row
       tables are runtime values — this is where the bytes are saved).
    3. REST pass over the (masked) remaining tiles; both passes' pools
       merge per query.

    Correctness invariant (property-tested): a pruned tile's bound is an
    upper bound on any of its docs' scores, and it is pruned only when
    strictly below theta_q <= final k-th score — so no true top-k doc is
    ever skipped. Match totals only count SCORED tiles: under pruning
    ``hits`` is a documented lower bound (WAND semantics), which is why
    exact-total consumers take the exhaustive path.

    q_real: how many leading rows of ``weights`` are real members (the
    rest are power-of-two padding); padded members never hold tiles
    alive. Returns (top_s [Q, k'], top_d [Q, k'], hits [Q] i32,
    tiles_scored i32 scalar).
    """
    if q_real is None:
        q_real = q_batch
    kw = dict(t_pad=t_pad, cb=cb, sub=sub, k=k, interpret=interpret,
              tiles_per_step=tiles_per_step, q_batch=q_batch, codec=codec)
    ts1, td1, th1 = score_tiles(
        docs_padded, frac_padded, live_t, rl_probe, rh_probe, weights,
        tile_ids=tid_probe, **kw)
    s1, d1, h1 = merge_tile_topk_batched(ts1, td1, th1, k)
    if s1.shape[1] >= k:
        kth = s1[:, k - 1]
    else:
        # fewer candidate slots than k: no threshold can be claimed
        kth = jnp.full((q_batch,), -jnp.inf, jnp.float32)
    # padding members (q >= q_real) must never keep a tile alive: their
    # bounds are 0 (all-zero weights) and 0 >= -inf would pin every tile
    theta = jnp.where(jnp.arange(q_batch) < q_real, kth,
                      jnp.float32(np.inf))
    survive = jnp.any(bounds_rest >= theta[None, :], axis=1)  # [n_rest]
    rl2 = jnp.where(survive[:, None], rl_rest, jnp.int32(0))
    rh2 = jnp.where(survive[:, None], rh_rest, jnp.int32(0))
    tid2 = jnp.where(survive, tid_rest, jnp.int32(0))
    ts2, td2, th2 = score_tiles(
        docs_padded, frac_padded, live_t, rl2, rh2, weights,
        tile_ids=tid2, **kw)
    s2, d2, h2 = merge_tile_topk_batched(ts2, td2, th2, k)
    pool_s = jnp.concatenate([s1, s2], axis=1)
    pool_d = jnp.concatenate([d1, d2], axis=1)
    top_s, top_i = lax.top_k(pool_s, min(k, pool_s.shape[1]))
    top_d = jnp.take_along_axis(pool_d, top_i, axis=1)
    hits = h1 + h2
    tiles_scored = (jnp.int32(tid_probe.shape[0])
                    + jnp.sum(survive.astype(jnp.int32)))
    return top_s, top_d, hits, tiles_scored


def build_live_t(live: np.ndarray, geom: TileGeometry) -> np.ndarray:
    """Host-side: live mask [>= nd_pad] bool/float -> the kernel's
    transposed tile layout [n_tiles * LANE, sub] f32."""
    sub, n_tiles = geom.tile_sub, geom.n_tiles
    flat = np.zeros(geom.nd_pad, np.float32)
    flat[: len(live)] = live[: geom.nd_pad].astype(np.float32)
    return np.ascontiguousarray(
        flat.reshape(n_tiles, sub, LANE).transpose(0, 2, 1)
    ).reshape(n_tiles * LANE, sub)


@functools.partial(jax.jit, static_argnames=("sub",))
def dense_to_flat(dense, sub: int):
    """Device-side: kernel dense output [n_tiles*LANE, sub] -> [nd_pad]
    in natural doc order (doc = tile*W + s*128 + lane)."""
    n_tiles = dense.shape[0] // LANE
    return dense.reshape(n_tiles, LANE, sub).transpose(0, 2, 1).reshape(-1)


# ----------------------------------------------------------------------
# Numpy reference (tests + CPU fallback parity)
# ----------------------------------------------------------------------


def reference_scores(
    block_docs: np.ndarray,
    block_frac: np.ndarray,
    lanes: Sequence[QueryLane],
    nd_pad: int,
) -> np.ndarray:
    """Dense scores via host scatter-add — the oracle the kernel must match."""
    scores = np.zeros(nd_pad, np.float32)
    for lane in lanes:
        if lane.block_count <= 0 or lane.weight == 0.0:
            continue
        rows = slice(lane.block_start, lane.block_start + lane.block_count)
        docs = block_docs[rows].ravel()
        frac = block_frac[rows].ravel()
        real = (frac > 0) & (docs < nd_pad)
        np.add.at(scores, docs[real], lane.weight * frac[real])
    return scores
