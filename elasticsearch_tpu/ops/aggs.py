"""Aggregation kernels: segment-sum buckets, stats, HLL++ cardinality.

Replaces the reference's per-doc collector tree
(search/aggregations/AggregatorBase, BucketsAggregator,
GlobalOrdinalsStringTermsAggregator, HyperLogLogPlusPlus) with dense
scatter-add programs over the matched-doc mask:

- terms agg     -> one-hot counts over the ordinal CSR column
                   (GlobalOrdinalsStringTermsAggregator's ordinal-array
                   counting, vectorized)
- histogram     -> bucket-id computation + segment-sum
- stats         -> masked reductions
- cardinality   -> HLL++ register scatter-max (HyperLogLogPlusPlus.java's
                   2^p registers in BigArrays ≙ a [2^p] int32 vector)

Partials are associative, so cross-segment and cross-shard reduction is a
plain elementwise combine — exactly the property the reference exploits in
InternalAggregation.doReduce, here mapped onto psum-style tree reduction
(SURVEY.md §5.7).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np


# beyond this many contributions per call the kernel's f32 accumulator
# could lose count exactness (2^24); the int32 scatter path stays exact
_PALLAS_COUNT_EXACT_LIMIT = 1 << 24


def _pallas_mode(n_entries: int = 0):
    """Bucket segment-sums route through the pallas kernel
    (ops/pallas_aggs.py) on TPU — XLA lowers `.at[].add` with duplicate
    indices to a serialized loop there. ES_TPU_PALLAS=off forces the
    scatter path; =interpret exercises the kernel on CPU (tests)."""
    if n_entries > _PALLAS_COUNT_EXACT_LIMIT:
        return None
    env = os.environ.get("ES_TPU_PALLAS", "auto")
    if env == "off":
        return None
    if env == "interpret":
        return "interpret"
    return "compiled" if jax.default_backend() == "tpu" else None


def _segsum(ords, contrib, n_ords: int, mode: str, values=None,
            sum_only: bool = False):
    """Run the pallas segment-sum (it pads to its chunk multiple itself)."""
    from elasticsearch_tpu.ops.pallas_aggs import segment_aggregate

    return segment_aggregate(
        jnp.asarray(ords, jnp.int32), jnp.asarray(contrib, jnp.float32),
        None if values is None else jnp.asarray(values, jnp.float32),
        n_ords=n_ords, with_sum=values is not None,
        with_count=not sum_only, interpret=(mode == "interpret"))


# ---------------------------------------------------------------------------
# Bucket aggs
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("n_ords",))
def _ordinal_counts_scatter(flat_docs, flat_ords, mask, n_ords: int):
    contrib = mask[flat_docs].astype(jnp.int32)
    return jnp.zeros((n_ords,), jnp.int32).at[flat_ords].add(contrib, mode="drop")


@functools.partial(jax.jit, static_argnames=("n_ords", "mode"))
def _ordinal_counts_pallas(flat_docs, flat_ords, mask, n_ords: int,
                           mode: str):
    contrib = jnp.where(mask[flat_docs], jnp.float32(1.0), jnp.float32(0.0))
    (cnt,) = _segsum(flat_ords, contrib, n_ords, mode)
    return cnt.astype(jnp.int32)


def ordinal_counts(flat_docs, flat_ords, mask, n_ords: int):
    """Per-ordinal doc counts over matched docs (terms agg heart).

    mask: [nd1] bool (matched & live). Multi-valued docs count once per
    distinct value (matches the reference: a doc adds 1 to each of its
    ordinals' buckets).
    """
    mode = _pallas_mode(flat_ords.shape[0])
    if mode:
        return _ordinal_counts_pallas(flat_docs, flat_ords, mask, n_ords,
                                      mode)
    return _ordinal_counts_scatter(flat_docs, flat_ords, mask, n_ords)


@functools.partial(jax.jit, static_argnames=("n_ords",))
def _ordinal_sums_scatter(flat_docs, flat_ords, mask, values_by_doc,
                          n_ords: int):
    contrib = jnp.where(mask[flat_docs], values_by_doc[flat_docs], 0.0)
    return jnp.zeros((n_ords,), jnp.float64).at[flat_ords].add(contrib, mode="drop")


@functools.partial(jax.jit, static_argnames=("n_ords", "mode"))
def _ordinal_sums_pallas(flat_docs, flat_ords, mask, values_by_doc,
                         n_ords: int, mode: str):
    contrib = jnp.where(mask[flat_docs], jnp.float32(1.0), jnp.float32(0.0))
    vals = values_by_doc[flat_docs].astype(jnp.float32)
    tot = _segsum(flat_ords, contrib, n_ords, mode, values=vals,
                  sum_only=True)[0]
    return tot.astype(jnp.float64)


def ordinal_sums(flat_docs, flat_ords, mask, values_by_doc, n_ords: int):
    """Sum of a per-doc metric value, bucketed by ordinal (terms + sub-sum).
    The pallas path accumulates in f32 (TPU has no f64); the CPU scatter
    path keeps f64."""
    mode = _pallas_mode(flat_ords.shape[0])
    if mode:
        return _ordinal_sums_pallas(flat_docs, flat_ords, mask,
                                    values_by_doc, n_ords, mode)
    return _ordinal_sums_scatter(flat_docs, flat_ords, mask, values_by_doc,
                                 n_ords)


@functools.partial(jax.jit, static_argnames=("n_buckets",))
def _histogram_counts_scatter(flat_docs, flat_values, mask, interval, offset,
                              min_bucket_key, n_buckets: int):
    bucket = jnp.floor((flat_values - offset) / interval).astype(jnp.int64) - min_bucket_key
    valid = mask[flat_docs] & (bucket >= 0) & (bucket < n_buckets)
    contrib = valid.astype(jnp.int32)
    bucket = jnp.clip(bucket, 0, n_buckets - 1)
    return jnp.zeros((n_buckets,), jnp.int32).at[bucket].add(contrib)


@functools.partial(jax.jit, static_argnames=("n_buckets", "mode"))
def _histogram_counts_pallas(flat_docs, flat_values, mask, interval, offset,
                             min_bucket_key, n_buckets: int, mode: str):
    # exact int64 rebase like the scatter path: date-histogram epoch-ms
    # keys would lose thousands of buckets to float rounding otherwise.
    # validity is checked on the int64 bucket BEFORE narrowing — an int32
    # cast of a far-out-of-range value would wrap into a valid bucket
    bucket64 = (jnp.floor((flat_values - offset) / interval)
                .astype(jnp.int64) - min_bucket_key)
    valid = mask[flat_docs] & (bucket64 >= 0) & (bucket64 < n_buckets)
    bucket = jnp.where(valid, bucket64, -1).astype(jnp.int32)
    contrib = jnp.where(valid, jnp.float32(1.0), jnp.float32(0.0))
    (cnt,) = _segsum(bucket, contrib, n_buckets, mode)
    return cnt.astype(jnp.int32)


def histogram_counts(flat_docs, flat_values, mask, interval, offset,
                     min_bucket_key, n_buckets: int):
    """Fixed-interval histogram: bucket = floor((v - offset)/interval),
    rebased by min_bucket_key; out-of-range values drop (callers size the
    bucket range from segment min/max so nothing real drops)."""
    mode = _pallas_mode(flat_values.shape[0])
    if mode:
        return _histogram_counts_pallas(
            jnp.asarray(flat_docs), jnp.asarray(flat_values),
            jnp.asarray(mask), interval, offset, min_bucket_key, n_buckets,
            mode)
    return _histogram_counts_scatter(flat_docs, flat_values, mask, interval,
                                     offset, min_bucket_key, n_buckets)


@functools.partial(jax.jit, static_argnames=("n_ranges",))
def range_counts(flat_docs, flat_values, mask, lo, hi, n_ranges: int):
    """Counts per [lo_i, hi_i) range (range agg; ranges may overlap).
    lo/hi: [n_ranges] float64. Counts DOCS (not values): a doc lands in a
    range once even if several of its values do."""
    nd1 = mask.shape[0]
    in_range = (flat_values[None, :] >= lo[:, None]) & (flat_values[None, :] < hi[:, None])
    # per-range doc mask via scatter-or, then masked popcount
    def one(r_mask):
        per_doc = jnp.zeros((nd1,), bool).at[flat_docs].max(r_mask)
        return jnp.sum((per_doc & mask).astype(jnp.int32))

    return jax.vmap(one)(in_range)


@functools.partial(jax.jit, static_argnames=("n_buckets",))
def _value_histogram_sums_scatter(flat_docs, flat_values, metric_by_doc, mask,
                                  interval, offset, min_bucket_key,
                                  n_buckets: int):
    bucket = jnp.floor((flat_values - offset) / interval).astype(jnp.int64) - min_bucket_key
    valid = mask[flat_docs] & (bucket >= 0) & (bucket < n_buckets)
    contrib = jnp.where(valid, metric_by_doc[flat_docs], 0.0)
    bucket = jnp.clip(bucket, 0, n_buckets - 1)
    return jnp.zeros((n_buckets,), jnp.float64).at[bucket].add(contrib)


@functools.partial(jax.jit, static_argnames=("n_buckets", "mode"))
def _value_histogram_sums_pallas(flat_docs, flat_values, metric_by_doc, mask,
                                 interval, offset, min_bucket_key,
                                 n_buckets: int, mode: str):
    bucket64 = (jnp.floor((flat_values - offset) / interval)
                .astype(jnp.int64) - min_bucket_key)
    valid = mask[flat_docs] & (bucket64 >= 0) & (bucket64 < n_buckets)
    bucket = jnp.where(valid, bucket64, -1).astype(jnp.int32)
    contrib = jnp.where(valid, jnp.float32(1.0), jnp.float32(0.0))
    vals = metric_by_doc[flat_docs].astype(jnp.float32)
    tot = _segsum(bucket, contrib, n_buckets, mode, values=vals,
                  sum_only=True)[0]
    return tot.astype(jnp.float64)


def value_histogram_sums(flat_docs, flat_values, metric_by_doc, mask, interval,
                         offset, min_bucket_key, n_buckets: int):
    """Sum of a per-doc metric grouped by histogram bucket of this field.
    Pallas path accumulates in f32 (TPU has no f64)."""
    mode = _pallas_mode(flat_values.shape[0])
    if mode:
        return _value_histogram_sums_pallas(
            jnp.asarray(flat_docs), jnp.asarray(flat_values),
            jnp.asarray(metric_by_doc), jnp.asarray(mask), interval, offset,
            min_bucket_key, n_buckets, mode)
    return _value_histogram_sums_scatter(flat_docs, flat_values,
                                         metric_by_doc, mask, interval,
                                         offset, min_bucket_key, n_buckets)


# ---------------------------------------------------------------------------
# Metric aggs
# ---------------------------------------------------------------------------


@jax.jit
def numeric_stats(flat_docs, flat_values, valid, mask):
    """(count, sum, min, max, sum_of_squares) over values of matched docs.

    valid: [n_vals] bool — real (non-padding) CSR entries.
    """
    sel = valid & mask[flat_docs]
    vals = jnp.where(sel, flat_values, 0.0)
    count = jnp.sum(sel.astype(jnp.int64))
    total = jnp.sum(vals)
    sq = jnp.sum(vals * vals)
    vmin = jnp.min(jnp.where(sel, flat_values, jnp.inf))
    vmax = jnp.max(jnp.where(sel, flat_values, -jnp.inf))
    return count, total, vmin, vmax, sq


@jax.jit
def value_count(flat_docs, valid, mask):
    return jnp.sum((valid & mask[flat_docs]).astype(jnp.int64))


# --- HyperLogLog++ ---------------------------------------------------------

HLL_DEFAULT_PRECISION = 14  # ES default precision_threshold≈3000 -> p≈14


def _fmix64(h):
    h = h.astype(jnp.uint64)
    h ^= h >> 33
    h *= jnp.uint64(0xFF51AFD7ED558CCD)
    h ^= h >> 33
    h *= jnp.uint64(0xC4CEB9FE1A85EC53)
    h ^= h >> 33
    return h


@functools.partial(jax.jit, static_argnames=("precision",))
def hll_registers(flat_docs, hashes, valid, mask, precision: int = HLL_DEFAULT_PRECISION):
    """Build HLL++ registers from per-value 64-bit hashes.

    hashes: [n_vals] uint64 (precomputed per ordinal/value, see
    hash_numeric_values / OrdinalColumn hashing at seal).
    Register j = max over values with bucket j of (position of first set
    bit of the remaining hash bits).
    """
    m = 1 << precision
    sel = valid & mask[flat_docs]
    h = _fmix64(hashes)
    bucket = (h >> jnp.uint64(64 - precision)).astype(jnp.int32)
    rest = (h << jnp.uint64(precision)) | jnp.uint64(1 << (precision - 1))
    # rho = number of leading zeros of `rest` + 1
    rho = (_clz64(rest) + 1).astype(jnp.int32)
    rho = jnp.where(sel, rho, 0)
    bucket = jnp.where(sel, bucket, 0)
    return jnp.zeros((m,), jnp.int32).at[bucket].max(rho)


def _clz64(x):
    x = x.astype(jnp.uint64)
    n = jnp.zeros(x.shape, jnp.int32)
    for shift in (32, 16, 8, 4, 2, 1):
        mask = x >= (jnp.uint64(1) << jnp.uint64(64 - shift))
        # if the top `shift` bits are empty, shift left and count
        empty = x < (jnp.uint64(1) << jnp.uint64(64 - shift))
        n = n + jnp.where(empty, shift, 0)
        x = jnp.where(empty, x << jnp.uint64(shift), x)
        del mask
    return jnp.where(x == 0, 64, n)


@jax.jit
def hll_merge(regs_a, regs_b):
    """Associative register merge (cross-segment / cross-shard reduce)."""
    return jnp.maximum(regs_a, regs_b)


def hll_estimate(registers: np.ndarray) -> float:
    """Harmonic-mean estimate with small-range correction (host-side; the
    reference's HyperLogLogPlusPlus.cardinality())."""
    regs = np.asarray(registers)
    m = regs.shape[0]
    alpha = 0.7213 / (1.0 + 1.079 / m)
    est = alpha * m * m / np.sum(np.power(2.0, -regs.astype(np.float64)))
    zeros = int(np.sum(regs == 0))
    if est <= 2.5 * m and zeros > 0:
        est = m * np.log(m / zeros)  # linear counting
    return float(est)


def hash_numeric_values(values: np.ndarray) -> np.ndarray:
    """Host-side 64-bit hashing of numeric values for HLL (at query time,
    once per segment column; cached). Uses the float64 bit pattern."""
    bits = np.asarray(values, dtype=np.float64).view(np.uint64)
    h = bits.copy()
    h ^= h >> 33
    h *= np.uint64(0xFF51AFD7ED558CCD)
    h ^= h >> 33
    return h


def hash_string_values(terms) -> np.ndarray:
    """Hash a term dictionary (ordinal -> hash) for HLL over keywords."""
    import hashlib

    out = np.empty(len(terms), dtype=np.uint64)
    for i, t in enumerate(terms):
        out[i] = np.frombuffer(
            hashlib.blake2b(t.encode("utf-8"), digest_size=8).digest(), dtype=np.uint64
        )[0]
    return out


# ---------------------------------------------------------------------------
# Percentiles (TDigest-lite: exact-on-device histogram of matched values is
# impractical for float ranges; we collect a bounded sample + exact small-n)
# ---------------------------------------------------------------------------


@jax.jit
def masked_values_for_sample(flat_docs, flat_values, valid, mask):
    """Values of matched docs with -inf elsewhere; host draws the sample/
    sorts exactly. For large segments a Pallas reservoir kernel replaces
    this (future work)."""
    sel = valid & mask[flat_docs]
    return jnp.where(sel, flat_values, jnp.nan)
