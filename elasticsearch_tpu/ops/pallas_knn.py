"""Pallas TPU kernel for dense-vector (kNN) retrieval on the MXU.

The BM25 tile-scoring plane (ops/pallas_scoring.py) is bandwidth-bound —
it streams posting bytes and does almost no arithmetic, so the TPU's
matrix units sit idle. This module adds the workload TPUs are literally
built for: brute-force kNN over a staged ``[nd_pad, d]`` bf16 embedding
matrix, scored tile-by-tile with a real MXU matmul (ROADMAP item 4; the
dense/hybrid retrieval scenario modern Elasticsearch grew into).

Design, mirroring the BM25 kernel's conventions so the two planes share
the serving machinery (micro-batching, plane ladder, quarantine):

- The doc space is partitioned into tiles of ``W = sub * 128`` docs. The
  kernel grid iterates tiles; each grid step DMAs one ``[W, d_pad]``
  bf16 block of the embedding matrix out of HBM (HALF the bytes of an
  f32 layout — bf16 storage is the codec), converts it to f32 in VMEM
  and contracts it against the whole query batch on the MXU:

      scoresT[W, Q] = emb_tile[W, d_pad] . qvecs[Q, d_pad]^T

  ONE corpus stream serves all Q queries of the batch — exactly the
  cross-query amortization the MicroBatcher exists for (``q_batch`` is
  the same static dim the BM25 kernel carries).
- Metrics: ``dot_product`` scores the raw inner product;``cosine``
  multiplies by a staged per-doc inverse-norm column (the query side is
  normalized host-side), so one kernel body serves both — the metric is
  a scale column, not a code path. Both are mapped through the
  reference's affine rescale ``(1 + sim) / 2`` so scores stay
  positive-ish and orderings match the ES convention.
- The per-tile top-k is fused: each tile emits its local top-K (scores,
  doc ids) per query via the same masked-select loop the BM25 kernel
  uses; the [n_tiles * K] candidate pools merge with one tiny
  ``lax.top_k`` per query. The dense score matrix never reaches HBM.
- Live/tombstone masking rides a staged ``[nd_pad, 1]`` f32 mask column
  (live AND has-vector): dead docs score -inf before the top-k, so
  deletes are honored without touching the embedding staging.
- The matmul runs ``Precision.HIGHEST``: the recall@10 == 1.0 gate vs
  the exact f32 numpy oracle is the bench's acceptance bar, and the
  default single-pass bf16 MXU rounding (~2^-8 relative) can reorder
  near-tied neighbors. bf16 already halved the HBM traffic the kernel
  is actually bound on; 6 extra MXU passes on a d=128 contraction are
  noise next to the stream.

All shapes are static and bucketed (d padded to a lane multiple, Q and K
padded to powers of two by the callers) so compiled programs cache
across queries.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
NEG_INF = float("-inf")

# default tile = 8192 docs: the [W, d_pad] f32-converted block must live
# in VMEM next to the bf16 copy and the [W, Q] score slab; at d=128 that
# is ~6.3 MB — comfortably under the ~16 MB/core budget while keeping
# the per-grid-step fixed cost (which dominates the BM25 kernel too)
# amortized over big tiles
DEFAULT_KNN_SUB = 64
# VMEM budget for the f32-converted embedding block; knn_tile_sub shrinks
# the tile for high-dimensional fields so the block always fits
KNN_TILE_F32_BUDGET = 8 * 1024 * 1024

VALID_KNN_SUBS = (8, 16, 32, 64, 128)

METRICS = ("cosine", "dot_product")


def pad_dims(dims: int) -> int:
    """Embedding columns pad to a lane multiple so the bf16 block's last
    dimension tiles cleanly on the VPU/MXU (zeros never change a dot)."""
    return max(((int(dims) + LANE - 1) // LANE) * LANE, LANE)


def knn_tile_sub(nd_pad: int, d_pad: int,
                 pref: int = DEFAULT_KNN_SUB) -> int:
    """Tile sublane count for a kNN launch: the preference (the
    ``search.knn.tile_sub`` setting), shrunk until the f32-converted
    embedding block fits the VMEM budget, floored at 8 (mosaic sublane
    granularity). The geometry helper shrinks further for small doc
    spaces on its own."""
    sub = pref if pref in VALID_KNN_SUBS else DEFAULT_KNN_SUB
    while sub > 8 and sub * LANE * d_pad * 4 > KNN_TILE_F32_BUDGET:
        sub //= 2
    return sub


def knn_geometry(nd_pad: int, d_pad: int, pref: int = DEFAULT_KNN_SUB):
    """TileGeometry for a kNN launch over an ``nd_pad`` doc space —
    reuses the BM25 plane's geometry type so callers share code."""
    from elasticsearch_tpu.ops.pallas_scoring import tile_geometry

    return tile_geometry(max(nd_pad, LANE), knn_tile_sub(nd_pad, d_pad,
                                                         pref))


def bf16_round(vectors: np.ndarray) -> np.ndarray:
    """Round an f32 host matrix to the bf16 grid (what the device stores
    and the kernel decodes) and return it as f32 — the host mirror the
    numpy oracle scores so recall gates compare like with like."""
    import ml_dtypes  # jax dependency; bakes the bf16 rounding rule

    return np.asarray(vectors, np.float32).astype(
        ml_dtypes.bfloat16).astype(np.float32)


def vector_scale_column(vectors_f32: np.ndarray, metric: str) -> np.ndarray:
    """Per-doc score scale [nd_pad, 1] f32: 1/|x| for cosine (docs with
    zero norm scale to 0 → score 0.5, ranked by nothing), all-ones for
    dot_product. ``vectors_f32``: the bf16-rounded host mirror."""
    if metric == "cosine":
        norms = np.linalg.norm(vectors_f32.astype(np.float32), axis=1)
        with np.errstate(divide="ignore"):
            inv = np.where(norms > 0.0, 1.0 / norms, 0.0)
        return inv.astype(np.float32).reshape(-1, 1)
    return np.ones((vectors_f32.shape[0], 1), np.float32)


def normalize_query(qvec: np.ndarray, metric: str,
                    d_pad: int) -> np.ndarray:
    """Query vector ready for the kernel/oracle: f32, zero-padded to
    ``d_pad``; cosine additionally folds 1/|q| into the vector (the doc
    side's 1/|x| rides the staged scale column)."""
    q = np.zeros(d_pad, np.float32)
    v = np.asarray(qvec, np.float32)
    q[: v.shape[0]] = v
    if metric == "cosine":
        n = float(np.linalg.norm(v))
        if n > 0.0:
            q[: v.shape[0]] = v / n
    return q


# ----------------------------------------------------------------------
# The kernel
# ----------------------------------------------------------------------


def _make_knn_kernel(sub: int, d_pad: int, k: int, q_batch: int):
    """Kernel body. Mosaic constraints shape the formulation the same way
    they shaped the BM25 kernel (see ops/pallas_scoring._make_kernel):
    every scalar literal is an explicit int32/float32 (weak python
    scalars trace to i64/f64 under the engine's x64 mode and crash the
    TPU compile), the top-k builds whole (k, Q) blocks with masked
    selects instead of scalar stores, and the score slab keeps docs on
    the SUBLANE axis so the live-mask column broadcasts along lanes."""
    w = sub * LANE

    def kernel(emb_ref, scale_ref, mask_ref, q_ref, out_s_ref, out_d_ref):
        t = pl.program_id(0)
        base = jnp.int32(t) * jnp.int32(w)
        # [W, d_pad] bf16 -> f32 in VMEM, then ONE MXU contraction for
        # the whole query batch: scoresT[W, Q]. HIGHEST precision — see
        # module docstring (the recall gate is the acceptance bar).
        emb = emb_ref[...].astype(jnp.float32)
        sT = lax.dot_general(
            emb, q_ref[...], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST)
        # metric scale column (cosine: 1/|x|; dot: ones) + the reference
        # affine rescale (1 + sim) / 2 — [W, 1] broadcasts over Q
        sT = sT * scale_ref[...] * jnp.float32(0.5) + jnp.float32(0.5)
        live = mask_ref[...] > jnp.float32(0.0)  # [W, 1]
        ninf = jnp.float32(NEG_INF)
        masked = jnp.where(live, sT, ninf)  # [W, Q]
        lin = lax.broadcasted_iota(jnp.int32, (w, q_batch), 0)
        outv_s = jnp.full((k, q_batch), NEG_INF, jnp.float32)
        outv_d = jnp.full((k, q_batch), -1, jnp.int32)
        k_iota = lax.broadcasted_iota(jnp.int32, (k, q_batch), 0)
        for i in range(k):
            mx = jnp.max(masked, axis=0, keepdims=True)  # [1, Q]
            sel = jnp.where(masked == mx, lin, jnp.int32(w))
            idx = jnp.min(sel, axis=0, keepdims=True)  # [1, Q]
            outv_s = jnp.where(k_iota == jnp.int32(i), mx, outv_s)
            doc = jnp.where(mx == ninf, jnp.int32(-1), base + idx)
            outv_d = jnp.where(k_iota == jnp.int32(i), doc, outv_d)
            masked = jnp.where(lin == idx, ninf, masked)
        out_s_ref[...] = outv_s.reshape(1, k, q_batch)
        out_d_ref[...] = outv_d.reshape(1, k, q_batch)

    return kernel


def _compiler_params():
    try:
        return pltpu.CompilerParams(dimension_semantics=("arbitrary",))
    except (TypeError, AttributeError):  # older/newer API drift
        return None


@functools.partial(
    jax.jit, static_argnames=("sub", "k", "q_batch", "interpret"))
def knn_score_tiles(
    emb,  # [nd_pad, d_pad] bf16 embedding matrix (rows beyond the real
    # docs are zero; the mask column kills them anyway)
    scale,  # [nd_pad, 1] f32 per-doc metric scale (vector_scale_column)
    mask,  # [nd_pad, 1] f32: 1.0 = live AND has a vector
    qvecs,  # [q_batch, d_pad] f32 query batch (normalize_query rows;
    # padding members are all-zero and their outputs are discarded)
    *,
    sub: int,
    k: int = 10,
    q_batch: int = 1,
    interpret: bool = False,
):
    """Run the MXU kNN kernel over a staged embedding matrix.

    Returns (tile_scores [n_tiles, k, q_batch] f32, tile_docs
    [n_tiles, k, q_batch] i32, -1 = empty) — per-tile fused top-k
    candidates, merged per query by ``merge_knn_topk``. The match TOTAL
    (live docs carrying a vector) is metric- and query-independent, so
    callers count it from the mask column instead of a kernel output.
    """
    nd_pad, d_pad = emb.shape
    w = sub * LANE
    if nd_pad % w:
        raise ValueError(f"nd_pad={nd_pad} not a multiple of tile {w}")
    n_tiles = nd_pad // w
    k = min(int(k), w)
    q_batch = max(1, int(q_batch))

    # index maps must return int32 (the engine runs with x64 enabled:
    # python-int literals become i64 constants inside mosaic transform
    # functions and crash the TPU compile helper)
    def zero():
        return jnp.int32(0)

    in_specs = [
        pl.BlockSpec((w, d_pad), lambda t: (t, zero())),
        pl.BlockSpec((w, 1), lambda t: (t, zero())),
        pl.BlockSpec((w, 1), lambda t: (t, zero())),
        pl.BlockSpec((q_batch, d_pad), lambda t: (zero(), zero())),
    ]
    out_specs = [
        pl.BlockSpec((1, k, q_batch), lambda t: (t, zero(), zero())),
        pl.BlockSpec((1, k, q_batch), lambda t: (t, zero(), zero())),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((n_tiles, k, q_batch), jnp.float32),
        jax.ShapeDtypeStruct((n_tiles, k, q_batch), jnp.int32),
    ]
    kernel = _make_knn_kernel(sub, d_pad, k, q_batch)
    kwargs = {}
    params = _compiler_params()
    if params is not None and not interpret:
        kwargs["compiler_params"] = params
    return pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=tuple(out_shape),
        interpret=interpret,
        **kwargs,
    )(emb, scale, mask, qvecs)


@functools.partial(jax.jit, static_argnames=("k",))
def merge_knn_topk(tile_scores, tile_docs, k: int):
    """Merge per-tile candidates per query: tile_scores/tile_docs are
    [n_tiles, kk, Q]; returns (top_s [Q, k'], top_d [Q, k'] i32) with
    k' = min(k, n_tiles * kk)."""
    n_tiles, kk, q = tile_scores.shape
    pool_s = tile_scores.transpose(2, 0, 1).reshape(q, -1)
    pool_d = tile_docs.transpose(2, 0, 1).reshape(q, -1)
    k2 = min(int(k), pool_s.shape[1])
    top_s, top_i = lax.top_k(pool_s, k2)
    top_d = jnp.take_along_axis(pool_d, top_i, axis=1)
    return top_s, top_d


# ----------------------------------------------------------------------
# Numpy reference (tests + bench recall gate + CPU fallback parity)
# ----------------------------------------------------------------------


def reference_knn_scores(vectors_f32: np.ndarray, qvec: np.ndarray,
                         metric: str = "cosine",
                         scale: Optional[np.ndarray] = None) -> np.ndarray:
    """Exact f32 scores over the bf16-rounded host mirror — the oracle
    the kernel (and the host plan node) must match. ``qvec`` is the RAW
    user vector; normalization/affine happen here exactly as staged."""
    qvec = np.asarray(qvec, np.float32)
    q = normalize_query(qvec, metric, max(vectors_f32.shape[1],
                                          qvec.shape[0]))
    s = vectors_f32.astype(np.float32) @ q[: vectors_f32.shape[1]]
    if scale is None:
        scale = vector_scale_column(vectors_f32, metric)
    return (s * scale[:, 0] * np.float32(0.5)
            + np.float32(0.5)).astype(np.float32)


def reference_knn_topk(vectors_f32: np.ndarray, mask: np.ndarray,
                       qvec: np.ndarray, k: int,
                       metric: str = "cosine") -> Tuple[np.ndarray,
                                                        np.ndarray]:
    """Exact top-k (scores, doc ids) over live vector docs."""
    s = reference_knn_scores(vectors_f32, qvec, metric)
    masked = np.where(mask[: len(s)], s, -np.inf)
    k = min(k, len(masked))
    idx = np.argpartition(-masked, k - 1)[:k] if k < len(masked) \
        else np.arange(len(masked))
    idx = idx[np.argsort(-masked[idx], kind="stable")]
    return masked[idx], idx
