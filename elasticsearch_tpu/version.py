"""Version constants.

Mirrors the role of the reference's ``Version`` class
(core/src/main/java/org/elasticsearch/Version.java) — a single place for
the engine version and the wire/index compatibility floor.
"""

__version__ = "0.1.0"

# Index format version written into segment metadata; bumped on
# incompatible changes to the on-disk segment layout.
INDEX_FORMAT_VERSION = 1

# Lucene-equivalent: version of the block-packed posting layout.
POSTING_FORMAT_VERSION = 1
