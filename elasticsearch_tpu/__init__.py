"""elasticsearch_tpu — a TPU-native distributed search & analytics engine.

Built from scratch in JAX/XLA with the capabilities of Elasticsearch
6.0.0-beta1 (reference: /root/reference), redesigned TPU-first:

- segments are block-packed dense arrays in HBM (not byte-compressed
  skip-list postings),
- per-shard query execution is a single jit-compiled program (BM25
  scatter-add scoring + ``lax.top_k``), not a virtual-call collector chain,
- cross-shard scatter/gather rides mesh collectives (``shard_map`` +
  ``psum``/``all_gather``) instead of an RPC data plane,
- the control plane (cluster state, mapping, REST) is host-side Python.

See SURVEY.md for the structural map of the reference this is built against.
"""

from elasticsearch_tpu.version import __version__

__all__ = ["__version__"]
