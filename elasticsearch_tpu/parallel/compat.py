"""jax version compatibility shims for the parallel execution layer."""

try:
    from jax import shard_map
except ImportError:  # pre-0.5 jax: experimental home + check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, check_vma=None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _shard_map(f, **kw)

__all__ = ["shard_map"]
