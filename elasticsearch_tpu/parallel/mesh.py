"""Device mesh construction for shard placement.

Role model inversion: the reference scales by placing Lucene shards on
nodes connected by Netty RPC (modules/transport-netty4). On TPU the
intra-slice "network" is ICI, addressed not by RPC but by compiling
collectives into the program over a ``jax.sharding.Mesh`` (SURVEY.md §5.8):

- axis "shards": index shards, one (or more) per device — the data-plane
  scatter/gather of the reference's query phase becomes psum/all_gather
  over this axis.
- axis "replicas" (optional 2nd axis): query replicas for throughput —
  the analog of replica shards serving reads.

Cross-host (DCN) communication stays host-side RPC (cluster/ control
plane), exactly as the reference separates data plane from cluster state.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def shard_mesh(n_shards: Optional[int] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the 'shards' axis."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_shards is not None:
        devs = devs[:n_shards]
    return Mesh(np.asarray(devs), axis_names=("shards",))


def shard_replica_mesh(n_shards: int, n_replicas: int,
                       devices: Optional[Sequence] = None) -> Mesh:
    """2-D mesh: shards x replicas (replicas see the same shard data and
    split query load)."""
    devs = list(devices) if devices is not None else jax.devices()
    need = n_shards * n_replicas
    if len(devs) < need:
        raise ValueError(
            f"need {need} devices for {n_shards}x{n_replicas} mesh, have {len(devs)}"
        )
    grid = np.asarray(devs[:need]).reshape(n_shards, n_replicas)
    return Mesh(grid, axis_names=("shards", "replicas"))


def shards_sharding(mesh: Mesh) -> NamedSharding:
    """Leading dim partitioned across shards."""
    return NamedSharding(mesh, PartitionSpec("shards"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
