"""The production mesh data plane: ANY query plan over a device mesh.

Round-1's `parallel/distributed.py` proved the collectives pattern on one
hardcoded disjunction kernel; this module generalizes it to the full query
DSL. The per-shard plans built by ``QueryBuilder.to_plan`` (identical tree
structure, shard-local arrays) are STACKED — every plan array padded to a
common shape with a leading ``[n_devices]`` axis — and the template plan's
``emit`` is traced ONCE inside ``shard_map``. The result is one compiled
XLA program executing the whole scatter-gather:

  per-device:  plan.emit -> (scores, matched) over the local shard
               -> local lax.top_k
  collective:  all_gather(top-k) over ICI -> global top-k on every device
               (the TopDocs.merge analog,
               action/search/SearchPhaseController.java:408)
               psum(total_hits) (+ psum'd agg partials, aggs_mesh.py)

Per-array padding semantics come from ``PlanNode.pad_kinds`` — padded
lanes either carry ``valid=False`` masks or scatter onto the stacked
sentinel doc (``nd1-1``), which ``live1`` kills.

Reference: the RPC fan-out this replaces is
action/search/AbstractSearchAsyncAction.java + SearchTransportService
("indices:data/read/search[phase/query]"), per SURVEY.md §5.7/§5.8.
"""

from __future__ import annotations

import functools
import itertools
import logging
import threading
import time as _time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from elasticsearch_tpu.parallel.compat import shard_map

from elasticsearch_tpu.search.plan import EmitCtx, PlanNode


class PlanStructureMismatch(Exception):
    """Per-shard plans for the same query diverged structurally (e.g. a
    field exists on one shard only with a different similarity) — the
    caller falls back to the host-merge path."""


from elasticsearch_tpu.common.staging import StagingBail  # noqa: E402


class _KnnStructuralError(StagingBail):
    """A dense_vector field cannot stage on this segment set (dims
    mismatch vs the mapping): permanent structural inability, never a
    device fault — ensure_knn pins the field to the host rung."""


class _DeltaIneligible(StagingBail):
    """A delta staging attempt hit a structural surprise the cheap
    eligibility pre-check could not see (ISSUE 20): not a device fault —
    run_staged re-raises it untouched (StagingBail contract) and
    IndexMeshSearch falls back to the full geometry rebuild."""


_plane_logger = logging.getLogger("elasticsearch_tpu.parallel.plane")

# Two mesh programs in flight at once interleave their collective
# rendezvous on the multi-device CPU backend (all_gather participants
# from different run_ids wait on each other — observed as a hang when
# concurrent REST threads each launch a shard_map program). A single
# chip executes programs serially anyway, so serializing mesh-program
# EXECUTION process-wide costs nothing on TPU and makes concurrent
# search traffic safe everywhere. Compilation/staging stay unlocked.
_MESH_EXEC_LOCK = threading.Lock()


class PlaneHealth:
    """Per-index execution-plane failure tracking + quarantine.

    A mesh_pallas / mesh plane that RAISES (compile error, device OOM,
    runtime fault — as opposed to a clean PlanStructureMismatch shape
    fallback) is benched for ``cooldown_s``: queries serve from the next
    rung of the ladder without re-paying the failure. After the cooldown
    the plane is HALF-OPEN: exactly ONE query is admitted as the probe
    (single-flight — ISSUE 10) while its peers keep serving the healthy
    rung, so a concurrent burst arriving at cooldown expiry never
    re-pays the fault N times. The probe's success re-opens the plane;
    its failure re-benches it for another cooldown. A probe that bails
    without executing (shape fallback, deadline) releases its admission;
    a prober that dies silently is covered by a bounded lease
    (``PROBE_LEASE_S``). Counters export via _stats planes
    (`plane_failures_total`, `plane_failures_by_reason`,
    `plane_quarantined`, `plane_probes_total`)."""

    PLANES = ("mesh_pallas", "mesh")
    MAX_EVENTS = 32
    # a probe admission expires after this long if the prober never
    # reported back (crashed thread) — the backstop, not the contract
    PROBE_LEASE_S = 30.0

    def __init__(self, cooldown_s: float = 60.0):
        self.cooldown_s = float(cooldown_s)
        self.failures_total: Dict[str, int] = {p: 0 for p in self.PLANES}
        # per-reason fault counters (ISSUE 10): `kernel_fault` = the
        # compiled program raised; `staging_fault` = a device staging
        # faulted terminally (classified transient-exhausted or
        # deterministic — see docs/RESILIENCE.md)
        self.failures_by_reason: Dict[str, int] = {}
        self.probes_total = 0
        self._quarantined_until: Dict[str, float] = {}
        self._probe_until: Dict[str, float] = {}
        self._lock = threading.Lock()
        # quarantine event log (docs/OBSERVABILITY.md): wall-clock
        # timestamps so operators can join a latency regression to the
        # fault that demoted the plane; capped, oldest dropped
        self.events: List[dict] = []

    def record_failure(self, plane: str,
                       reason: str = "kernel_fault") -> None:
        with self._lock:
            self.failures_total[plane] = \
                self.failures_total.get(plane, 0) + 1
            self.failures_by_reason[reason] = \
                self.failures_by_reason.get(reason, 0) + 1
            self._quarantined_until[plane] = (_time.monotonic()
                                              + self.cooldown_s)
            self._probe_until.pop(plane, None)
            self.events.append({
                "plane": plane,
                "reason": reason,
                "timestamp_ms": int(_time.time() * 1000),
                "cooldown_s": self.cooldown_s,
            })
            if len(self.events) > self.MAX_EVENTS:
                del self.events[0]

    def admit(self, plane: str) -> str:
        """Single-flight admission gate for the ladder: ``"open"`` =
        plane healthy, attempt freely; ``"probe"`` = the caller is THE
        post-cooldown probe (it must end in note_success /
        record_failure / release_probe); ``""`` (falsy) = benched, or a
        peer's probe is in flight — serve the next rung."""
        now = _time.monotonic()
        with self._lock:
            until = self._quarantined_until.get(plane)
            if until is None:
                return "open"
            if now < until:
                return ""
            lease = self._probe_until.get(plane, 0.0)
            if now < lease:
                return ""  # a peer is probing: single-flight
            self._probe_until[plane] = now + self.PROBE_LEASE_S
            self.probes_total += 1
            return "probe"

    def note_success(self, plane: str) -> None:
        """The plane served a query to completion: fully re-open it
        (clears any quarantine + probe lease; no-op when healthy)."""
        if plane not in self._quarantined_until:
            return  # lock-free fast path for the healthy hot path
        with self._lock:
            self._quarantined_until.pop(plane, None)
            self._probe_until.pop(plane, None)

    def release_probe(self, plane: str) -> None:
        """The probe bailed without executing the plane (shape
        fallback, staging ineligibility, deadline): hand the admission
        back so the next query may probe. Idempotent; never clears a
        quarantine record_failure re-armed. An un-consumed admission is
        also un-COUNTED — ``plane_probes_total`` reports probes that
        actually reached a verdict (success or failure), so a plane
        that turned structurally ineligible while benched doesn't grow
        the counter one admission per query forever."""
        with self._lock:
            if self._probe_until.pop(plane, None) is not None:
                self.probes_total -= 1

    def available(self, plane: str) -> bool:
        """Non-consuming view (stats + cheap pre-checks): False only
        while benched inside the cooldown. A half-open plane reads as
        available — use ``admit`` on the serving path."""
        return _time.monotonic() >= self._quarantined_until.get(plane, 0.0)

    def quarantined(self) -> List[str]:
        now = _time.monotonic()
        return [p for p, until in sorted(self._quarantined_until.items())
                if now < until]

    def stats(self) -> dict:
        return {
            "plane_failures_total": dict(self.failures_total),
            "plane_failures_by_reason": dict(self.failures_by_reason),
            "plane_probes_total": self.probes_total,
            "plane_quarantined": self.quarantined(),
            "quarantine_events": list(self.events),
        }


def _check_same_structure(plans: List[PlanNode]) -> None:
    def skeleton(p: PlanNode):
        # trace_statics participates: a static parameter baked into the
        # template's trace (similarity kinds, range relation, boost_mode)
        # that diverges per shard would silently score non-template
        # shards with the wrong formula
        return (type(p).__name__, len(p.arrays()), p.trace_statics(),
                tuple(skeleton(c) for c in p.children()))

    first = skeleton(plans[0])
    for p in plans[1:]:
        if skeleton(p) != first:
            raise PlanStructureMismatch(
                f"{skeleton(p)} != {first}")


_PAD_VALUES = {"z": 0, "o": 1, "n": np.nan, "m1": -1}


def stack_plans(plans: List[PlanNode], local_nd_pads: List[int],
                stacked_nd1: int, n_slots: int) -> List[np.ndarray]:
    """Stack per-shard plan arrays to mesh-ready arrays.

    Returns a flat list aligned with ``template.flat_arrays()`` where every
    entry has a leading [n_slots] axis (slots = device x segments-packed-
    per-device). Slots beyond len(plans) replicate shard 0's arrays —
    their seg arrays have live1 all-False (and zero kernel frac), so they
    contribute nothing.
    """
    _check_same_structure(plans)
    kinds = plans[0].flat_pad_kinds()
    try:
        flats = [[np.asarray(a) for a in p.flat_arrays()] for p in plans]
    except NotImplementedError:
        # an unfinalized mesh kernel node — not stackable in this form
        raise PlanStructureMismatch("plan contains unfinalized arrays")
    n_arrays = len(kinds)
    for f in flats:
        if len(f) != n_arrays:
            raise PlanStructureMismatch("flat array count mismatch")
    sentinel = stacked_nd1 - 1
    stacked: List[np.ndarray] = []
    for i, kind in enumerate(kinds):
        if kind == "x":
            # non-stackable node — the host per-shard path serves these
            raise PlanStructureMismatch("plan contains non-stackable arrays")
        parts = [f[i] for f in flats]
        if kind == "k":
            # kernel tables: stack verbatim, but ONLY when every shard's
            # tables were harmonized to one shape (the kernel trace is
            # shared — a shape divergence means harmonization didn't run
            # and the plan must not reach the mesh program)
            if len({(p.shape, str(p.dtype)) for p in parts}) != 1:
                raise PlanStructureMismatch("kernel table shapes diverge")
            parts = parts + [parts[0]] * (n_slots - len(parts))
            stacked.append(np.stack(parts))
            continue
        # replicate shard 0 into unused slots
        parts = parts + [parts[0]] * (n_slots - len(parts))
        if kind == "s" or parts[0].ndim == 0:
            stacked.append(np.stack([np.asarray(p) for p in parts]))
            continue
        if kind == "dense":
            tail = parts[0].shape[1:]
            out = np.zeros((n_slots, stacked_nd1) + tail, parts[0].dtype)
            for d, a in enumerate(parts):
                out[d, : a.shape[0]] = a
            stacked.append(out)
            continue
        max_shape = tuple(
            max(p.shape[j] for p in parts) for j in range(parts[0].ndim)
        )
        if kind == "d":
            out = np.full((n_slots,) + max_shape, sentinel,
                          dtype=parts[0].dtype)
        else:
            out = np.full((n_slots,) + max_shape, _PAD_VALUES[kind],
                          dtype=parts[0].dtype)
        for d, a in enumerate(parts):
            if kind == "d":
                # re-point the shard-local sentinel doc to the stacked
                # one (replicated filler slots came from shard 0)
                src_shard = d if d < len(plans) else 0
                a = np.where(a == local_nd_pads[src_shard], sentinel, a)
            out[(d,) + tuple(slice(0, s) for s in a.shape)] = a
        stacked.append(out)
    return stacked


def _strip_plan(p: PlanNode) -> PlanNode:
    """Structural clone with data arrays dropped.

    emit() reads data exclusively through ``ctx.take`` during tracing;
    only static attributes (kinds, relation, boost_mode, child lists,
    ``len(factor_columns)``) are consulted on ``self``. Caching the full
    template would pin up to maxsize copies of doc-sized numpy columns
    (e.g. FunctionScoreNode factor columns) for the process lifetime."""
    import copy

    q = copy.copy(p)
    for name, val in vars(q).items():
        if isinstance(val, np.ndarray) and val.size > 8:
            setattr(q, name, None)
        elif isinstance(val, PlanNode):
            setattr(q, name, _strip_plan(val))
        elif isinstance(val, list) and val:
            if all(isinstance(v, PlanNode) for v in val):
                setattr(q, name, [_strip_plan(c) for c in val])
            elif all(isinstance(v, np.ndarray) for v in val):
                # length is trace-relevant (ctx.take count); contents not
                setattr(q, name, [None] * len(val))
    return q


class _TemplateHolder:
    """lru_cache key: plan structure + stacked shapes; holds the
    array-stripped template plans (main, post_filter, rescore) whose
    emit() defines the trace (same pattern as plan.py)."""

    __slots__ = ("plan", "pf_plan", "rs_plan", "_key")

    def __init__(self, plan: PlanNode, key: str,
                 pf_plan: Optional[PlanNode] = None,
                 rs_plan: Optional[PlanNode] = None):
        self.plan = plan
        self.pf_plan = pf_plan
        self.rs_plan = rs_plan
        self._key = key

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, _TemplateHolder) and self._key == other._key


@functools.lru_cache(maxsize=128)
def _mesh_query_program(mesh: Mesh, holder: _TemplateHolder, k: int,
                        spd: int = 1,
                        sort_keys: Optional[Tuple[str, str]] = None,
                        with_views: bool = False,
                        features: frozenset = frozenset(),
                        slice_col: Optional[str] = None,
                        rescore_static: Optional[Tuple[int, str]] = None,
                        agg_static: tuple = ()):
    """One compiled scatter-gather program covering the collector-chain
    semantics of the reference's query phase (QueryPhase.java:179-268) as
    fused mask stages:

      emit -> live -> min_score -> slice -> [agg view] -> post_filter ->
      total psum -> search_after cut -> (rescore window pass) ->
      local top-k -> all_gather global merge

    spd: SLOTS per device. A device packs spd segments (the reference's
    data node searching any number of Lucene leaves per shard,
    search/internal/ContextIndexSearcher.java:53); the per-slot query
    phases are unrolled into the device program, their candidates
    concatenated before the ICI merge. spd=1 is the historical
    one-segment-per-device layout.
    sort_keys: None ranks by score; (key_name, raw_name) ranks by the
    staged oriented key column and carries the raw field values for the
    response's per-hit ``sort`` array (FieldSortBuilder semantics).
    with_views: additionally return the per-slot matched masks and
    scores (sharded, no collective) — the aggregation reduce consumes
    them as SegmentViews exactly like the host path's shard partials.
    features: which traced scalars participate ("min_score",
    "search_after"); their VALUES arrive via the `scalars` argument so
    pagination does not recompile.
    rescore_static: (window_size, score_mode) — QueryRescorer's window
    pass over the per-slot (== per-segment, matching the host's
    per-segment window) top candidates; weights are traced scalars.
    agg_static: fused-aggregation descriptors (search/fused_aggs.py) —
    each slot's agg-visible matched mask reduces into tiny per-spec
    partial accumulators INSIDE this program (same launch as scoring;
    the masks never leave the device), returned sharded per slot like
    the views. Mutually exclusive with with_views.
    """
    plan = holder.plan
    pf_plan = holder.pf_plan
    rs_plan = holder.rs_plan

    def per_slot(seg, plan_arrays, pf_arrays, rs_arrays, scalars):
        """One segment's query phase: emit -> mask stages -> local top-k.
        Returns (loc_keys, loc_docs, loc_scores, loc_raw|None,
        local_count, agg_matched, scores)."""
        ctx = EmitCtx(seg, plan_arrays)
        scores, matched = plan.emit(ctx)
        matched = matched & seg["live1"]
        # stage order mirrors the host path (search/service.py query()):
        # min_score and slice filter BEFORE aggs see the mask;
        # post_filter only narrows hits+total, never aggregations
        if "min_score" in features:
            matched = matched & (scores >= scalars["min_score"])
        if slice_col is not None:
            matched = matched & seg[slice_col]
        agg_matched = matched
        if pf_plan is not None:
            pf_ctx = EmitCtx(seg, pf_arrays)
            _, pf_matched = pf_plan.emit(pf_ctx)
            matched = matched & pf_matched
        # per-slot matched count is also returned sharded: a slot is
        # one SEGMENT, but terminate_after caps per SHARD — the caller
        # groups segment counts by shard and applies the cap host-side
        local_count = jnp.sum(matched.astype(jnp.int32))
        if sort_keys is None:
            rank_key = scores
        else:
            rank_key = seg[sort_keys[0]]
        masked = jnp.where(matched, rank_key, -jnp.inf)
        if "search_after" in features:
            # strict 'after' cut in oriented-key space: desc keys are the
            # raw values, asc keys their negation, so "comes after the
            # cursor" is uniformly key < after_key (hits only — total is
            # unaffected, same as TopFieldCollector paging)
            masked = jnp.where(rank_key < scalars["search_after"],
                               masked, -jnp.inf)
        nd = masked.shape[0]
        if rs_plan is not None:
            # QueryRescorer window pass. Candidates = the host path's
            # k_select = max(k, window) per segment; the first `window`
            # of them (by original rank) get combined scores, the rest
            # keep their original score; ranking then happens over the
            # candidate set ONLY — a doc outside it can never re-enter,
            # exactly like the host's seg_refs list.
            window, score_mode = rescore_static
            ksel = min(max(k, window), nd)
            sel_keys, sel_docs = jax.lax.top_k(masked, ksel)
            rs_ctx = EmitCtx(seg, rs_arrays)
            rs_scores, _ = rs_plan.emit(rs_ctx)
            w = min(window, ksel)
            rs_sel = rs_scores[sel_docs[:w]]
            qw = scalars["query_weight"]
            rqw = scalars["rescore_query_weight"]
            base = sel_keys[:w] * qw
            resc = rs_sel * rqw
            if score_mode == "total":
                comb = base + resc
            elif score_mode == "multiply":
                comb = jnp.where(rs_sel != 0.0, base * rs_sel, base)
            elif score_mode == "avg":
                comb = (base + resc) / 2.0
            elif score_mode == "max":
                comb = jnp.maximum(base, resc)
            elif score_mode == "min":
                comb = jnp.minimum(base, resc)
            else:
                raise ValueError(f"score_mode {score_mode}")
            # max/min could resurrect a -inf (unmatched/padding) lane
            comb = jnp.where(sel_keys[:w] == -jnp.inf, -jnp.inf, comb)
            cand_keys = jnp.concatenate([comb, sel_keys[w:]])
            kk = min(k, ksel)
            # rescoring reorders candidates, so ties in the COMBINED
            # score must re-break by doc id to match the host's
            # (-score, local_doc) sort — a plain top_k would keep
            # original-rank order for ties (score_mode max/min produce
            # exact ties routinely). Lexicographic (-score, doc) sort:
            neg_sorted, docs_sorted = jax.lax.sort(
                (-cand_keys, sel_docs), num_keys=2)
            loc_keys = -neg_sorted[:kk]
            loc_docs = docs_sorted[:kk]
            loc_scores = loc_keys  # the rescored score IS the hit score
        else:
            kk = min(k, nd)
            loc_keys, loc_docs = jax.lax.top_k(masked, kk)
            loc_scores = scores[loc_docs]
        loc_raw = None
        if sort_keys is not None:
            loc_raw = seg[sort_keys[1]][loc_docs]
        agg_parts = ()
        if agg_static:
            from elasticsearch_tpu.search.fused_aggs import (
                emit_agg_partials,
            )

            agg_parts = tuple(emit_agg_partials(agg_static, seg,
                                                agg_matched))
        return (loc_keys, loc_docs, loc_scores, loc_raw, local_count,
                agg_matched, scores, agg_parts)

    def per_device(seg, plan_arrays, pf_arrays, rs_arrays, scalars):
        dev = jax.lax.axis_index("shards")
        slot_out = []
        for i in range(spd):
            seg_i = {name: a[i] for name, a in seg.items()}
            slot_out.append(per_slot(
                seg_i, [a[i] for a in plan_arrays],
                [a[i] for a in pf_arrays], [a[i] for a in rs_arrays],
                scalars))
        kk = slot_out[0][0].shape[0]
        cand_keys = jnp.concatenate([o[0] for o in slot_out])
        cand_docs = jnp.concatenate([o[1] for o in slot_out])
        cand_scores = jnp.concatenate([o[2] for o in slot_out])
        # GLOBAL slot id per candidate: shard_map splits the [n_slots]
        # leading axis contiguously, so device d owns slots [d*spd, ...)
        cand_slot = (dev.astype(jnp.int32) * jnp.int32(spd)
                     + jnp.repeat(jnp.arange(spd, dtype=jnp.int32), kk))
        counts = jnp.stack([o[4] for o in slot_out])  # [spd]
        total = jax.lax.psum(jnp.sum(counts), "shards")
        # global merge over ICI: every device holds the same global top-k.
        # The merged pool holds n_slots*kk candidates, so the global cut
        # is min(k, pool) — NOT kk: when k exceeds one segment's padded
        # doc count, hits beyond the largest segment are still real.
        all_keys = jax.lax.all_gather(cand_keys, "shards").reshape(-1)
        all_docs = jax.lax.all_gather(cand_docs, "shards").reshape(-1)
        all_scores = jax.lax.all_gather(cand_scores, "shards").reshape(-1)
        all_slot = jax.lax.all_gather(cand_slot, "shards").reshape(-1)
        top_keys, top_idx = jax.lax.top_k(
            all_keys, min(k, all_keys.shape[0]))
        top_slot = all_slot[top_idx]
        top_doc = all_docs[top_idx]
        top_score = all_scores[top_idx]
        if sort_keys is None:
            top_raw = top_keys if rs_plan is None else top_score
        else:
            cand_raw = jnp.concatenate([o[3] for o in slot_out])
            all_raw = jax.lax.all_gather(cand_raw, "shards").reshape(-1)
            top_raw = all_raw[top_idx]
        outs = [top_keys[None], top_slot[None], top_doc[None],
                total[None], top_score[None], top_raw[None],
                counts]
        if with_views:
            outs.extend([jnp.stack([o[5] for o in slot_out]),
                         jnp.stack([o[6] for o in slot_out])])
        if agg_static:
            n_agg = len(slot_out[0][7])
            outs.extend(jnp.stack([o[7][j] for o in slot_out])
                        for j in range(n_agg))
        return tuple(outs)

    # 6 replicated merge outputs; local_count (index 6), the optional
    # views, and the fused-agg partials stay SHARDED (a row per slot)
    from elasticsearch_tpu.search.fused_aggs import n_agg_outputs

    n_merged = 6
    n_out = 7 + (2 if with_views else 0) + n_agg_outputs(agg_static)
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(PS("shards"), PS("shards"), PS("shards"), PS("shards"),
                  PS()),
        out_specs=(PS("shards"),) * n_out,
        check_vma=False,
    )

    @jax.jit
    def run(seg, plan_arrays, pf_arrays, rs_arrays, scalars):
        outs = mapped(seg, plan_arrays, pf_arrays, rs_arrays, scalars)
        # merged outputs are replicated (row 0 == row i); view outputs
        # keep their sharded leading axis
        merged = tuple(o[0] for o in outs[:n_merged])
        return merged + tuple(outs[n_merged:])

    from elasticsearch_tpu.common.compile_cache import (
        instrument_program,
        variant_key,
    )

    return instrument_program(
        run, "serial",
        variant_key("serial", holder._key, len(mesh.devices)))


def _shapes_sig(arrays) -> str:
    return ";".join(f"{a.shape}{a.dtype}" for a in arrays)


@functools.lru_cache(maxsize=32)
def _mesh_batched_kernel_program(mesh: Mesh, spd: int, q_batch: int,
                                 kk: int, t_pad: int, cb: int, sub: int,
                                 tps: int, interpret: bool,
                                 codec: str = "raw"):
    """One compiled scatter-gather serving Q CONCURRENT queries (ISSUE 5
    cross-query micro-batching on the mesh_pallas rung): per slot, ONE
    batched ``score_tiles`` launch streams the slot's posting windows
    once and emits per-query per-tile top-k candidates; the per-query
    pools merge locally, then over ICI via one all_gather — the same
    collective shape as _mesh_query_program's merge, with a leading
    query axis instead of a leading 1. codec="packed" streams the
    bit-packed posting words (one corpus operand instead of two)."""
    from elasticsearch_tpu.ops import pallas_scoring as psc

    packed = codec == "packed"

    def per_device(*args):
        if packed:
            kp, lt, rl, rh, w = args
        else:
            kd, kf, lt, rl, rh, w = args
        dev = jax.lax.axis_index("shards")
        cand_s, cand_d, cand_slot = [], [], []
        hits = None
        for i in range(spd):
            corpus = (kp[i], None) if packed else (kd[i], kf[i])
            ts_, td_, th_ = psc.score_tiles(
                corpus[0], corpus[1], lt[i], rl[i], rh[i], w[i],
                t_pad=t_pad, cb=cb, sub=sub, k=kk, interpret=interpret,
                tiles_per_step=tps, q_batch=q_batch, codec=codec)
            s_i, d_i, h_i = psc.merge_tile_topk_batched(ts_, td_, th_, kk)
            cand_s.append(s_i)  # [Q, kk']
            cand_d.append(d_i)
            cand_slot.append(
                jnp.zeros(s_i.shape, jnp.int32)
                + (dev.astype(jnp.int32) * jnp.int32(spd) + jnp.int32(i)))
            hits = h_i if hits is None else hits + h_i
        cs = jnp.concatenate(cand_s, axis=1)
        cd = jnp.concatenate(cand_d, axis=1)
        cslot = jnp.concatenate(cand_slot, axis=1)
        total = jax.lax.psum(hits, "shards")  # [Q]
        all_s = jax.lax.all_gather(cs, "shards")  # [n_dev, Q, spd*kk']
        all_d = jax.lax.all_gather(cd, "shards")
        all_slot = jax.lax.all_gather(cslot, "shards")
        pool_s = all_s.transpose(1, 0, 2).reshape(q_batch, -1)
        pool_d = all_d.transpose(1, 0, 2).reshape(q_batch, -1)
        pool_slot = all_slot.transpose(1, 0, 2).reshape(q_batch, -1)
        top_s, top_i = jax.lax.top_k(pool_s, min(kk, pool_s.shape[1]))
        top_d = jnp.take_along_axis(pool_d, top_i, axis=1)
        top_slot = jnp.take_along_axis(pool_slot, top_i, axis=1)
        return top_s[None], top_d[None], top_slot[None], total[None]

    n_in = 5 if packed else 6
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(PS("shards"),) * n_in,
        out_specs=(PS("shards"),) * 4,
        check_vma=False,
    )

    @jax.jit
    def run(*args):
        outs = mapped(*args)
        return tuple(o[0] for o in outs)  # replicated: row 0 == row i

    from elasticsearch_tpu.common.compile_cache import (
        instrument_program,
        variant_key,
    )

    return instrument_program(
        run, "batched",
        variant_key("batched", len(mesh.devices), spd, q_batch, kk,
                    t_pad, cb, sub, tps, interpret, codec))


@functools.lru_cache(maxsize=32)
def _mesh_batched_dense_agg_program(mesh: Mesh, spd: int, q_batch: int,
                                    kk: int, t_pad: int, cb: int, sub: int,
                                    tps: int, interpret: bool, codec: str,
                                    agg_statics: tuple, nd1: int):
    """The batched mesh program for agg-carrying bursts (ISSUE 13):
    ONE dense ``score_tiles`` launch streams each slot's posting
    windows once for the whole batch, and the SAME pass both ranks and
    aggregates — per member, the dense score vector yields the matched
    mask on device, the mask reduces the staged doc-value columns into
    per-spec partial accumulators (search/fused_aggs.py), and hits
    merge with the serial mesh program's exact collector semantics
    (per-slot ``lax.top_k`` over doc-ordered dense scores, pool concat
    in slot order, ICI all_gather, global top-k — byte-identical ties
    to the host path). ``agg_statics``: one fused-agg descriptor tuple
    per member (empty = member carries no aggs); heterogeneous bodies
    compile per combination, bucketed by the same q_pad/kk shape keys
    as the fused-top-k program. Aggs force this exhaustive dense form —
    pruning never composes with aggregations (docs/PRUNING.md)."""
    from elasticsearch_tpu.ops import pallas_scoring as psc
    from elasticsearch_tpu.search.fused_aggs import emit_agg_partials

    packed = codec == "packed"

    def per_device(*args):
        if packed:
            kp, lt, rl, rh, w, cols = args
        else:
            kd, kf, lt, rl, rh, w, cols = args
        dev = jax.lax.axis_index("shards")
        cand_s, cand_d, cand_slot = [], [], []
        counts = None
        agg_parts = None
        for i in range(spd):
            corpus = (kp[i], None) if packed else (kd[i], kf[i])
            dense = psc.score_tiles(
                corpus[0], corpus[1], lt[i], rl[i], rh[i], w[i],
                t_pad=t_pad, cb=cb, sub=sub, dense=True,
                interpret=interpret, tiles_per_step=tps,
                q_batch=q_batch, codec=codec)[0]
            rows = dense.shape[1] // psc.LANE
            flat = dense.reshape(q_batch, rows, psc.LANE, sub).transpose(
                0, 1, 3, 2).reshape(q_batch, -1)[:, : nd1 - 1]
            # sentinel column: dead like the serial program's live1 tail
            flat = jnp.concatenate(
                [flat, jnp.zeros((q_batch, 1), jnp.float32)], axis=1)
            matched = flat > 0.0  # [Q, nd1] (live folded in-kernel)
            masked = jnp.where(matched, flat, -jnp.inf)
            s_i, d_i = jax.lax.top_k(masked, min(kk, masked.shape[1]))
            cand_s.append(s_i)
            cand_d.append(d_i)
            cand_slot.append(
                jnp.zeros(s_i.shape, jnp.int32)
                + (dev.astype(jnp.int32) * jnp.int32(spd) + jnp.int32(i)))
            c = jnp.sum(matched.astype(jnp.int32), axis=1)  # [Q]
            counts = c if counts is None else counts + c
            cols_i = {name: a[i] for name, a in cols.items()}
            slot_parts = []
            for q in range(q_batch):
                if agg_statics[q]:
                    slot_parts.extend(emit_agg_partials(
                        agg_statics[q], cols_i, matched[q]))
            if agg_parts is None:
                agg_parts = [[p] for p in slot_parts]
            else:
                for j, p in enumerate(slot_parts):
                    agg_parts[j].append(p)
        cs = jnp.concatenate(cand_s, axis=1)
        cd = jnp.concatenate(cand_d, axis=1)
        cslot = jnp.concatenate(cand_slot, axis=1)
        total = jax.lax.psum(counts, "shards")  # [Q]
        all_s = jax.lax.all_gather(cs, "shards")
        all_d = jax.lax.all_gather(cd, "shards")
        all_slot = jax.lax.all_gather(cslot, "shards")
        pool_s = all_s.transpose(1, 0, 2).reshape(q_batch, -1)
        pool_d = all_d.transpose(1, 0, 2).reshape(q_batch, -1)
        pool_slot = all_slot.transpose(1, 0, 2).reshape(q_batch, -1)
        top_s, top_i = jax.lax.top_k(pool_s, min(kk, pool_s.shape[1]))
        top_d = jnp.take_along_axis(pool_d, top_i, axis=1)
        top_slot = jnp.take_along_axis(pool_slot, top_i, axis=1)
        outs = [top_s[None], top_d[None], top_slot[None], total[None]]
        if agg_parts:
            outs.extend(jnp.stack(parts) for parts in agg_parts)
        return tuple(outs)

    from elasticsearch_tpu.search.fused_aggs import n_agg_outputs

    n_agg_out = sum(n_agg_outputs(s) for s in agg_statics)
    n_in = 6 if packed else 7
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(PS("shards"),) * n_in,
        out_specs=(PS("shards"),) * (4 + n_agg_out),
        check_vma=False,
    )

    @jax.jit
    def run(*args):
        outs = mapped(*args)
        # merged outputs replicated; agg partials stay sharded per slot
        return tuple(o[0] for o in outs[:4]) + tuple(outs[4:])

    from elasticsearch_tpu.common.compile_cache import (
        instrument_program,
        variant_key,
    )

    return instrument_program(
        run, "batched_agg",
        variant_key("batched_agg", len(mesh.devices), spd, q_batch, kk,
                    t_pad, cb, sub, tps, interpret, codec, agg_statics,
                    nd1))


@functools.lru_cache(maxsize=32)
def _mesh_batched_pruned_program(mesh: Mesh, spd: int, q_batch: int,
                                 kk: int, t_pad: int,
                                 cb: int, sub: int, tps: int,
                                 interpret: bool, codec: str,
                                 probe: int, n_rest: int):
    """Block-max pruned batched scoring on the mesh (ISSUE 6), ONE
    compiled program with NO host round-trip:

    - probe pass: every slot scores its ``probe`` highest-bound tiles
      (host-ordered); the per-query candidate pools merge over ICI via
      all_gather — the k-th best merged score is the GLOBAL running
      threshold theta_q, identical on every device (deterministic merge
      of a replicated pool).
    - rest pass: each slot keeps only the rest tiles whose per-(tile,
      query) bound can still beat theta (a tile survives when ANY real
      member needs it — per-member thresholds over the union lanes, no
      cross-member leakage); non-survivors get their runtime row tables
      zeroed, which the sel-mode kernel turns into skipped DMA + compute.
    - both pools merge per query over ICI; totals are the psum of SCORED
      tiles' match counts (a documented lower bound under pruning).

    ``q_real`` (how many leading weight rows are real members — the rest
    are power-of-two padding) and ``slot_real`` (1 for staged segment
    slots, 0 for replication filler) are RUNTIME operands, not cache
    keys: arrival-timing-dependent batch sizes must not compile a
    program variant each, and filler slots must not inflate the tile
    counters (their bounds would otherwise survive any -inf threshold).

    Returns (top_s [Q, kk], top_d, top_slot, total [Q],
    tiles_scored scalar, tiles_total scalar)."""
    from elasticsearch_tpu.ops import pallas_scoring as psc

    packed = codec == "packed"

    def per_device(*args):
        if packed:
            (kp, lt, rl_p, rh_p, tid_p, rl_r, rh_r, tid_r, bounds_r,
             w, slot_real, q_real) = args
        else:
            (kd, kf, lt, rl_p, rh_p, tid_p, rl_r, rh_r, tid_r, bounds_r,
             w, slot_real, q_real) = args
        dev = jax.lax.axis_index("shards")
        kw = dict(t_pad=t_pad, cb=cb, sub=sub, k=kk, interpret=interpret,
                  tiles_per_step=tps, q_batch=q_batch, codec=codec)

        def slot_pass(i, rl, rh, tid):
            corpus = (kp[i], None) if packed else (kd[i], kf[i])
            ts_, td_, th_ = psc.score_tiles(
                corpus[0], corpus[1], lt[i], rl, rh, w[i],
                tile_ids=tid, **kw)
            s_i, d_i, h_i = psc.merge_tile_topk_batched(ts_, td_, th_, kk)
            slot = (jnp.zeros(s_i.shape, jnp.int32)
                    + (dev.astype(jnp.int32) * jnp.int32(spd)
                       + jnp.int32(i)))
            return s_i, d_i, slot, h_i

        def gather_pool(cand):
            cs = jnp.concatenate([c[0] for c in cand], axis=1)
            cd = jnp.concatenate([c[1] for c in cand], axis=1)
            cslot = jnp.concatenate([c[2] for c in cand], axis=1)
            all_s = jax.lax.all_gather(cs, "shards")
            all_d = jax.lax.all_gather(cd, "shards")
            all_slot = jax.lax.all_gather(cslot, "shards")
            return (all_s.transpose(1, 0, 2).reshape(q_batch, -1),
                    all_d.transpose(1, 0, 2).reshape(q_batch, -1),
                    all_slot.transpose(1, 0, 2).reshape(q_batch, -1))

        probe_out = [slot_pass(i, rl_p[i], rh_p[i], tid_p[i])
                     for i in range(spd)]
        hits = sum(o[3] for o in probe_out[1:]) + probe_out[0][3]
        pool_s, pool_d, pool_slot = gather_pool(probe_out)
        # global running threshold: k-th best of the merged probe pool
        # (replicated — every device computes the identical theta)
        kth_s, _ = jax.lax.top_k(pool_s, min(kk, pool_s.shape[1]))
        if kth_s.shape[1] >= kk:
            kth = kth_s[:, kk - 1]
        else:
            kth = jnp.full((q_batch,), -jnp.inf, jnp.float32)
        theta = jnp.where(jnp.arange(q_batch) < q_real, kth,
                          jnp.float32(np.inf))
        # filler slots (slot_real == 0) must never survive: their -inf
        # bounds would pass a member's -inf threshold and inflate the
        # counters (their tables are all-zero, so scoring them is only
        # an accounting bug — but the pruned fraction is this feature's
        # headline observable)
        real_mask = slot_real > jnp.int32(0)  # [spd]
        survive = (jnp.any(bounds_r >= theta[None, None, :], axis=2)
                   & real_mask[:, None])
        rest_out = []
        for i in range(spd):
            sv = survive[i]
            rl2 = jnp.where(sv[:, None], rl_r[i], jnp.int32(0))
            rh2 = jnp.where(sv[:, None], rh_r[i], jnp.int32(0))
            tid2 = jnp.where(sv, tid_r[i], jnp.int32(0))
            rest_out.append(slot_pass(i, rl2, rh2, tid2))
        hits = hits + sum(o[3] for o in rest_out[1:]) + rest_out[0][3]
        rs, rd, rslot = gather_pool(rest_out)
        pool_s = jnp.concatenate([pool_s, rs], axis=1)
        pool_d = jnp.concatenate([pool_d, rd], axis=1)
        pool_slot = jnp.concatenate([pool_slot, rslot], axis=1)
        top_s, top_i = jax.lax.top_k(pool_s, min(kk, pool_s.shape[1]))
        top_d = jnp.take_along_axis(pool_d, top_i, axis=1)
        top_slot = jnp.take_along_axis(pool_slot, top_i, axis=1)
        total = jax.lax.psum(hits, "shards")
        n_real = jnp.sum(slot_real)
        scored = jax.lax.psum(
            n_real * jnp.int32(probe)
            + jnp.sum(survive.astype(jnp.int32)), "shards")
        tiles_total = jax.lax.psum(
            n_real * jnp.int32(probe + n_rest), "shards")
        return (top_s[None], top_d[None], top_slot[None], total[None],
                scored[None], tiles_total[None])

    n_in = 11 if packed else 12
    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(PS("shards"),) * n_in + (PS(),),
        out_specs=(PS("shards"),) * 6,
        check_vma=False,
    )

    @jax.jit
    def run(*args):
        outs = mapped(*args)
        return tuple(o[0] for o in outs)  # replicated: row 0 == row i

    from elasticsearch_tpu.common.compile_cache import (
        instrument_program,
        variant_key,
    )

    return instrument_program(
        run, "pruned",
        variant_key("pruned", len(mesh.devices), spd, q_batch, kk, t_pad,
                    cb, sub, tps, interpret, codec, probe, n_rest))


@functools.lru_cache(maxsize=32)
def _mesh_knn_program(mesh: Mesh, spd: int, q_pad: int, kk: int,
                      sub: int, d_pad: int, nd_knn: int,
                      interpret: bool):
    """One compiled scatter-gather serving Q concurrent kNN queries on
    the MXU (ROADMAP item 4): per slot, ONE ``knn_score_tiles`` launch
    streams the slot's bf16 embedding matrix once for the whole batch
    and emits per-query per-tile top-k candidates; pools merge locally,
    then over ICI via one all_gather — the same collective shape as
    ``_mesh_batched_kernel_program``, with the posting windows replaced
    by a dense matmul. The match total (live docs carrying the vector
    field) is query-independent: it is the psum of the staged mask
    sums, not a kernel output."""
    from elasticsearch_tpu.ops import pallas_knn as pkn

    def per_device(emb, scale, mask, qv):
        dev = jax.lax.axis_index("shards")
        cand_s, cand_d, cand_slot = [], [], []
        count = None
        for i in range(spd):
            ts, td = pkn.knn_score_tiles(
                emb[i], scale[i], mask[i], qv,
                sub=sub, k=kk, q_batch=q_pad, interpret=interpret)
            s_i, d_i = pkn.merge_knn_topk(ts, td, kk)  # [q_pad, kk']
            cand_s.append(s_i)
            cand_d.append(d_i)
            cand_slot.append(
                jnp.zeros(s_i.shape, jnp.int32)
                + (dev.astype(jnp.int32) * jnp.int32(spd) + jnp.int32(i)))
            c = jnp.sum(mask[i]).astype(jnp.int32)
            count = c if count is None else count + c
        cs = jnp.concatenate(cand_s, axis=1)
        cd = jnp.concatenate(cand_d, axis=1)
        cslot = jnp.concatenate(cand_slot, axis=1)
        total = jax.lax.psum(count, "shards")  # scalar, replicated
        all_s = jax.lax.all_gather(cs, "shards")
        all_d = jax.lax.all_gather(cd, "shards")
        all_slot = jax.lax.all_gather(cslot, "shards")
        pool_s = all_s.transpose(1, 0, 2).reshape(q_pad, -1)
        pool_d = all_d.transpose(1, 0, 2).reshape(q_pad, -1)
        pool_slot = all_slot.transpose(1, 0, 2).reshape(q_pad, -1)
        top_s, top_i = jax.lax.top_k(pool_s, min(kk, pool_s.shape[1]))
        top_d = jnp.take_along_axis(pool_d, top_i, axis=1)
        top_slot = jnp.take_along_axis(pool_slot, top_i, axis=1)
        totals = jnp.full((q_pad,), total, jnp.int32)
        return top_s[None], top_d[None], top_slot[None], totals[None]

    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(PS("shards"), PS("shards"), PS("shards"), PS()),
        out_specs=(PS("shards"),) * 4,
        check_vma=False,
    )

    @jax.jit
    def run(*args):
        outs = mapped(*args)
        return tuple(o[0] for o in outs)  # replicated: row 0 == row i

    from elasticsearch_tpu.common.compile_cache import (
        instrument_program,
        variant_key,
    )

    return instrument_program(
        run, "knn",
        variant_key("knn", len(mesh.devices), spd, q_pad, kk, sub,
                    d_pad, nd_knn, interpret))


def clear_compiled_programs() -> None:
    """Drop every cached compiled-program entry (all five lru_cache'd
    mesh-program builders). Used by the rolling-restart soak and the
    cold_start bench to simulate a fresh process: the next query (or
    warm replay) re-traces and re-compiles — against the persistent
    compilation cache when one is configured."""
    for builder in (_mesh_query_program, _mesh_batched_kernel_program,
                    _mesh_batched_dense_agg_program,
                    _mesh_batched_pruned_program, _mesh_knn_program):
        builder.cache_clear()


class IndexMeshSearch:
    """Routes an index's production query phase through the mesh.

    Owned by IndexService. Eligible searches (plain query + top-k by
    score) run as ONE multi-device program over all (shard, segment)
    pairs; anything the program doesn't cover yet returns None and the
    caller uses the host-merge path — same shape as the reference
    choosing between query-then-fetch variants per request.

    Staging is cached against the identity of the segment set and
    invalidated automatically when any shard refreshes/merges."""

    # request keys the mesh program does not cover — presence of any of
    # them falls back to the host path. Everything else in the query
    # phase runs in-program: single-field f32-exact numeric/_doc/_score
    # AND keyword (global-ordinal) sorts, aggregations (reduced over the
    # program's per-device matched masks), post_filter / min_score /
    # slice as fused mask stages, search_after as an oriented-key cut,
    # rescore as an in-program window pass, terminate_after as the
    # host-identical reported-total cap. suggest and highlight are
    # host-side phases orthogonal to the query program (fetch/suggest
    # phases), served on the mesh path by the same code as the host path.
    # "profile" is NOT here (ISSUE 8): a profiled query runs on whatever
    # plane would serve it unprofiled and reports THAT plane's phase
    # spans — plane-truthful, never plane-demoting (docs/OBSERVABILITY.md).
    UNSUPPORTED = ("collapse",)

    def __init__(self, index_service, mesh: Optional[Mesh] = None):
        self.svc = index_service
        self._mesh = mesh
        self._executor: Optional[MeshPlanExecutor] = None
        self._staged_key = None
        self._pairs: List[Tuple[int, object]] = []  # (shard_id, segment)
        self.query_total = 0
        # queries whose scoring ran on the tile kernel inside the mesh
        # program (the unified fast plane) vs the XLA scatter formulation
        self.pallas_query_total = 0
        # cross-query micro-batching on the mesh_pallas rung
        # (query_batch): launches and member-queries served batched
        self.batched_launch_total = 0
        self.batched_query_total = 0
        # dense-vector retrieval on the MXU (docs/VECTOR.md): queries
        # whose kNN side ran the mesh kNN program
        self.knn_query_total = 0
        # fused on-device aggregations (ISSUE 13, docs/AGGS.md):
        # queries whose whole agg set reduced inside the mesh program,
        # vs agg'd mesh queries that fell back to the host reduce over
        # device views — per documented reason (docs/OBSERVABILITY.md)
        self.agg_fused_query_total = 0
        self.agg_host_fallback_total = 0
        self.agg_host_fallback_by_reason: Dict[str, int] = {}
        # block-max pruned scoring observability (docs/PRUNING.md):
        # queries served by the pruned program, and its tile economy
        self.pruned_query_total = 0
        self.tiles_scored_total = 0
        self.tiles_pruned_total = 0
        # delta device staging (ISSUE 20, docs/MESH.md): refreshes
        # served by a slot append instead of a rebuild, deletes served
        # by in-place tombstone mask updates, and background compaction
        # passes that rebuilt a compact generation
        self.delta_restage_total = 0
        self.tombstone_update_total = 0
        self.compaction_runs_total = 0
        settings = getattr(index_service, "settings", None)
        # packing limit: segments are packed max_slots-deep per device
        # before the index falls back to the host path (registered as
        # index.search.mesh.max_slots_per_device)
        self.max_slots = 4
        # plane override: auto = kernel when stageable, scatter fallback;
        # pallas = kernel or host (never the scatter mesh); scatter =
        # never build kernel plans (index.search.mesh.plane)
        self.plane_pref = "auto"
        quarantine_cooldown = 60.0
        if settings is not None:
            self.max_slots = settings.get_int(
                "index.search.mesh.max_slots_per_device", 4)
            self.plane_pref = settings.get_str(
                "index.search.mesh.plane", "auto")
            quarantine_cooldown = settings.get_time(
                "index.search.plane_quarantine.cooldown", 60.0)
        # plane-health quarantine (index.search.plane_quarantine.cooldown)
        self.plane_health = PlaneHealth(quarantine_cooldown)
        # set by _ensure_staged when the HBM budget (not an infra gap)
        # turned the mesh staging away — exported as the ladder
        # decision reason so operators can tell demotion from fault.
        # THREAD-local: concurrent queries each read the reason their
        # own _ensure_staged call produced (a shared field would let one
        # thread's reset misattribute another's hbm_budget decision)
        self._denied = threading.local()
        # counter updates must be atomic: concurrent batch leaders /
        # serial queries increment from different threads (ISSUE 8
        # stats-consistency contract — docs/OBSERVABILITY.md)
        self._counter_lock = threading.Lock()
        # serializes the executor build/swap in _ensure_staged: two
        # concurrent first-queries must not both construct a generation
        # (the loser's staged bytes would leak in the ledger until index
        # close). _drop_staging deliberately does NOT take this lock —
        # the accountant invokes it under its own lock and a stager
        # inside this lock may be waiting on the accountant's.
        self._stage_lock = threading.Lock()
        # staging-fault bench state (ISSUE 10): a terminal (classified)
        # staging fault benches the mesh staging until this monotonic
        # deadline; after it, exactly one query probes the restage
        # (_stage_probing) while peers serve the host rung
        self._staging_fault_until = 0.0
        self._staging_faulted = False
        self._stage_probing = False

    @property
    def staging_denied_reason(self):
        return getattr(self._denied, "reason", None)

    @staging_denied_reason.setter
    def staging_denied_reason(self, value) -> None:
        self._denied.reason = value

    def _note(self, plane: str, reason: str, n: int = 1) -> None:
        """Plane-ladder decision counter (search.phases.decisions).
        ``n``: member count — batch-path decisions count per QUERY so
        they stay comparable with the serial ladder's counts."""
        tel = getattr(self.svc, "telemetry", None)
        if tel is not None:
            tel.note_decision(plane, reason, n)

    def _mesh_or_default(self) -> Mesh:
        if self._mesh is None:
            from elasticsearch_tpu.parallel.mesh import shard_mesh

            self._mesh = shard_mesh()
        return self._mesh

    def _current_pairs(self) -> List[Tuple[int, object]]:
        pairs = []
        for sid in sorted(self.svc.shards):
            eng = self.svc.shards[sid].engine
            for seg in eng.searchable_segments():
                if seg.num_docs > 0:
                    pairs.append((sid, seg))
        return pairs

    def _drop_staging(self) -> None:
        """HBM-budget eviction callback: drop the staged mesh plane (it
        restages on the next eligible query — or demotes to the host
        rung if the budget still can't fit it)."""
        executor, self._executor = self._executor, None
        self._staged_key = None
        if executor is not None:
            self._evicted_since = True
            executor.release()

    def _restage_reason(self, old_key, new_key, old_executor,
                        n_slots_needed: int) -> str:
        """Classify WHY the mesh plane restages (the staging lifecycle
        event reason, docs/OBSERVABILITY.md): a slot-geometry change,
        a segment-set change (refresh/merge), an in-place live-mask
        invalidation (deletes), or a re-stage after a budget eviction
        (probe — each executor generation is a fresh ledger scope, so
        the accountant cannot infer this one itself)."""
        if old_key is None or old_executor is None:
            if getattr(self, "_evicted_since", False):
                self._evicted_since = False
                return "probe"
            return "initial"
        if old_executor.n_slots != n_slots_needed:
            return "geometry_change"
        if ({(sid, seg_id) for sid, seg_id, _n in old_key}
                != {(sid, seg_id) for sid, seg_id, _n in new_key}):
            return "refresh"
        return "delete_invalidation"

    @staticmethod
    def _key_for(pairs) -> frozenset:
        """Staged-set identity: ORDER-INDEPENDENT (a frozenset), so a
        delta-append successor — whose slot order appends new segments
        at the tail instead of re-sorting — compares equal to the same
        logical set (ISSUE 20). live_doc_count participates: deletes
        mutate a sealed segment's live mask in place, which must
        invalidate (tombstone-update) the staged live1."""
        return frozenset((sid, id(seg), seg.live_doc_count)
                         for sid, seg in pairs)

    def _delta_enabled(self) -> bool:
        """index.staging.delta.enabled with the explicitness-aware
        cluster override on top (put_cluster_settings)."""
        override = getattr(self.svc, "staging_delta_enabled_override",
                           None)
        if override is not None:
            return bool(override)
        settings = getattr(self.svc, "settings", None)
        if settings is None:
            return True
        return bool(settings.get_bool("index.staging.delta.enabled",
                                      True))

    def _classify_delta(self, old, pairs, codec):
        """Decide whether the staged-key change is servable as a DELTA
        on the live generation (ISSUE 20). Returns
        ``("tombstone", [], changed_slots)`` when only live-doc counts
        changed, ``("append", new_pairs, changed_slots)`` when segments
        were added within free slot capacity (deletes may ride along),
        or None for the full-rebuild fallback (segments retired, slots
        exhausted, tile-geometry mismatch, codec change)."""
        staged_counts = {(sid, kid): n
                         for sid, kid, n in self._staged_key}
        slot_of = {(sid, id(seg)): slot
                   for slot, (sid, seg) in enumerate(old.pairs)}
        if set(slot_of) != set(staged_counts):
            return None  # key/generation disagree: rebuild from truth
        new_ids = {(sid, id(seg)) for sid, seg in pairs}
        if not set(slot_of) <= new_ids:
            return None  # segments retired (merge): rebuild
        if codec != old.postings_codec_pref:
            return None  # codec change: rebuild fallback
        append_pairs = [(sid, seg) for sid, seg in pairs
                        if (sid, id(seg)) not in slot_of]
        changed = sorted(
            slot_of[(sid, id(seg))] for sid, seg in pairs
            if (sid, id(seg)) in slot_of
            and staged_counts[(sid, id(seg))] != seg.live_doc_count)
        if not append_pairs:
            return ("tombstone", [], changed) if changed else None
        if not MeshPlanExecutor.delta_append_compatible(
                old, [seg for _sid, seg in append_pairs]):
            return None
        return ("append", append_pairs, changed)

    def _apply_delta(self, old, delta, key) -> Optional[bool]:
        """Serve a classified delta on/over the live generation (caller
        holds ``_stage_lock``). Returns True on success, False on a
        terminal fault (staging benched — host rung serves), or None
        when a structural surprise says fall back to the rebuild."""
        from elasticsearch_tpu.common.errors import \
            TaskCancelledException
        from elasticsearch_tpu.common.memory import memory_accountant
        from elasticsearch_tpu.common.staging import run_staged
        from elasticsearch_tpu.search.cancellation import \
            TimeExceededException

        # thread-local hygiene (PR-9 bug class): this is a staging
        # attempt in its own right — reset before any denial below
        self.staging_denied_reason = None
        kind_of, append_pairs, changed_slots = delta
        try:
            if kind_of == "tombstone":
                run_staged(
                    lambda: old.apply_tombstones(changed_slots),
                    index=self.svc.name, kind="live_mask", plane="mesh")
                self._staged_key = key
                with self._counter_lock:
                    self.tombstone_update_total += 1
                old.touch()
                self._maybe_compact()
                return True
            # append: budget-gate the DELTA rows only (the carried
            # arrays are already in the ledger under the old scope)
            estimate = sum(
                seg.block_docs.nbytes + seg.block_tfs.nbytes
                + seg.norms.nbytes + seg.nd_pad + 1
                for _sid, seg in append_pairs)
            if not memory_accountant().try_reserve(
                    self.svc.name, estimate, exclude_scope=old.scope):
                self.staging_denied_reason = "hbm_budget"
                return False
            staged = run_staged(
                lambda: MeshPlanExecutor.delta_append(
                    old, append_pairs, changed_slots,
                    index_name=self.svc.name),
                index=self.svc.name, kind="mesh_slot_tables",
                plane="mesh")
            old.release()
            self._pairs = list(staged.pairs)
            self._executor = staged
            self._staged_key = key
            with self._counter_lock:
                self.delta_restage_total += 1
                if changed_slots:
                    self.tombstone_update_total += 1
            staged.make_evictable(self._drop_staging)
            self._maybe_compact()
            return True
        except _DeltaIneligible:
            return None  # structural surprise: full rebuild fallback
        except (TaskCancelledException, TimeExceededException):
            raise  # PR-4 contract: caller owns partial/cancel
        except Exception:  # noqa: BLE001 — terminal classified staging
            # fault: same bench + quarantine as a full-rebuild fault
            # (the attempt rolled back; pre-attempt ledger is exact)
            _plane_logger.warning(
                "[%s] mesh delta staging failed; serving from the host "
                "rung for %.1fs (reason staging_fault)",
                self.svc.name, self.plane_health.cooldown_s,
                exc_info=True)
            self._staging_faulted = True
            self._staging_fault_until = (
                _time.monotonic() + self.plane_health.cooldown_s)
            self.plane_health.record_failure(
                "mesh_pallas", reason="staging_fault")
            self.staging_denied_reason = "staging_fault"
            return False

    def _maybe_compact(self) -> None:
        """Opportunistic compaction trigger after a delta commit: the
        owner decides (threshold/fragmentation/drain) and runs it OFF
        the query path (ISSUE 20 — no polling loop to leak)."""
        hook = getattr(self.svc, "maybe_compact_async", None)
        if hook is not None:
            hook()

    def _ensure_staged(self) -> bool:
        self.staging_denied_reason = None
        # staging-fault backoff (ISSUE 10, docs/RESILIENCE.md): after a
        # terminal staging fault the mesh staging is benched for the
        # quarantine cooldown — every query until then demotes to the
        # host rung (reason staging_fault) instead of re-paying the
        # multi-second staging attempt per query
        if _time.monotonic() < self._staging_fault_until:
            self.staging_denied_reason = "staging_fault"
            return False
        pairs = self._current_pairs()
        if not pairs:
            return False
        mesh = self._mesh_or_default()
        if len(pairs) > mesh.devices.size * max(self.max_slots, 1):
            return False  # packing bound (not a one-segment-per-device cap)
        key = self._key_for(pairs)
        # the "or executor is None" leg self-heals any state where the
        # staged key survived but the executor didn't (an eviction
        # racing an install): the next query restages instead of being
        # stuck demoted until the segment set changes
        if key != self._staged_key or self._executor is None:
            if self._stage_probing:
                # single-flight restage probe: a post-fault restage
                # attempt is in flight on a peer — don't pile onto the
                # lock behind a staging that may fault again; serve the
                # host rung until the probe commits (racy read: worst
                # case we wait on the lock like any cold staging)
                self.staging_denied_reason = "staging_fault"
                return False
            with self._stage_lock:
                executor = self._executor
                if key == self._staged_key and executor is not None:
                    # another query staged this exact segment set while
                    # we waited — reuse its generation
                    executor.touch()
                    return True
                if _time.monotonic() < self._staging_fault_until:
                    # a concurrent attempt faulted while we waited
                    self.staging_denied_reason = "staging_fault"
                    return False
                settings = getattr(self.svc, "settings", None)
                codec = (settings.get_str(
                    "index.search.pallas.postings_codec", "default")
                    if settings is not None else None)
                # ---- delta paths (ISSUE 20): tombstone a delete /
                # append new segments into free slots, keeping the
                # collective geometry — the rebuild below becomes the
                # FALLBACK (slots exhausted, tile-geometry mismatch,
                # codec change), not the default
                old = self._executor
                if (old is not None and self._staged_key is not None
                        and not self._staging_faulted
                        and self._delta_enabled()):
                    delta = self._classify_delta(old, pairs, codec)
                    if delta is not None:
                        handled = self._apply_delta(old, delta, key)
                        if handled is not None:
                            return handled
                return self._stage_rebuild(mesh, pairs, key, codec)
        else:
            executor = self._executor
            if executor is not None:
                executor.touch()
        return self._executor is not None

    def _stage_rebuild(self, mesh, pairs, key, codec,
                       reason: Optional[str] = None) -> bool:
        """Full-generation build + install (caller holds _stage_lock).
        The pre-ISSUE-20 default, now the delta paths' fallback — and
        the compaction pass's restage (reason="compaction")."""
        from elasticsearch_tpu.common.memory import memory_accountant
        from elasticsearch_tpu.common.staging import run_staged

        # thread-local hygiene (PR-9 bug class): a fresh staging
        # attempt — reset before any denial below (also covers the
        # compaction thread entering via restage_for_compaction)
        self.staging_denied_reason = None
        n_dev = mesh.devices.size
        spd = max(1, -(-len(pairs) // n_dev))
        if self._delta_enabled() and spd < max(self.max_slots, 1):
            # slot-allocator headroom (ISSUE 20): spare slots for ONE
            # refresh's worth of appended segments (a refresh seals at
            # most one per shard) so the NEXT refresh delta-appends
            # instead of rebuilding — bounded by the packing limit
            extra = max(1, -(-len(self.svc.shards) // n_dev))
            spd = min(spd + extra, max(self.max_slots, 1))
        n_slots = spd * n_dev
        # HBM budget gate (search.memory.hbm_budget_bytes): the
        # gate uses a cheap per-slot estimate — the ledger
        # records the EXACT bytes once staged. Denial demotes
        # this query (and every one until the budget frees) to
        # the host rung with ladder decision reason hbm_budget
        # — degrade, never 5xx.
        estimate = n_slots * max(
            seg.block_docs.nbytes + seg.block_tfs.nbytes
            + seg.norms.nbytes + seg.nd_pad + 1
            for _sid, seg in pairs)
        if not memory_accountant().try_reserve(self.svc.name,
                                               estimate):
            self.staging_denied_reason = "hbm_budget"
            return False
        if reason is None:
            reason = self._restage_reason(self._staged_key, key,
                                          self._executor, n_slots)
        if self._staging_faulted:
            self._stage_probing = True
        old = self._executor
        # construct UNARMED (not yet evictable), install, THEN
        # arm: a budget eviction firing mid-construction would
        # otherwise run _drop_staging against the PREVIOUS
        # generation and the install below would pin a staged
        # key whose executor is gone (see make_evictable).
        # The construction is one transactional staging attempt
        # (register-then-commit: a constructor fault registers
        # nothing) run through the classified retry loop —
        # transient device faults back off and retry, terminal
        # faults bench the staging AND quarantine the kernel
        # plane with reason staging_fault. The retry budget is
        # the PROCESS-level config (node file + live cluster
        # updates via configure_staging_retry) — NOT the index's
        # create-time Settings snapshot, which would freeze it
        # against later dynamic updates.
        from elasticsearch_tpu.common.errors import \
            TaskCancelledException
        from elasticsearch_tpu.search.cancellation import \
            TimeExceededException

        try:
            staged = run_staged(
                lambda: MeshPlanExecutor(
                    [seg for _, seg in pairs], mesh,
                    postings_codec=codec,
                    index_name=self.svc.name,
                    stage_reason=reason,
                    slots_per_dev=spd),
                index=self.svc.name, kind="mesh_slot_tables",
                plane="mesh")
        except (TaskCancelledException, TimeExceededException):
            raise  # PR-4 contract: caller owns partial/cancel —
            # never bench the staging for a dead query
        except Exception:  # noqa: BLE001 — terminal classified
            # staging fault: bench the staging for the cooldown
            # and quarantine the plane so _stats planes tells
            # staging_fault from kernel_fault (docs/RESILIENCE.md)
            _plane_logger.warning(
                "[%s] mesh staging failed; serving from the host "
                "rung for %.1fs (reason staging_fault)",
                self.svc.name, self.plane_health.cooldown_s,
                exc_info=True)
            self._staging_faulted = True
            self._staging_fault_until = (
                _time.monotonic() + self.plane_health.cooldown_s)
            self.plane_health.record_failure(
                "mesh_pallas", reason="staging_fault")
            self.staging_denied_reason = "staging_fault"
            return False
        finally:
            self._stage_probing = False
        staged.pairs = pairs
        if old is not None:
            old.release()
        self._pairs = pairs
        self._executor = staged
        self._staged_key = key
        self._staging_faulted = False
        self._staging_fault_until = 0.0
        staged.make_evictable(self._drop_staging)
        return True

    def staging_slot_stats(self) -> Optional[dict]:
        """Live-generation slot occupancy (ISSUE 20): per-device free
        slot capacity + per-slot tombstone density — the _cat/staging
        operator surface and the compaction trigger's inputs. None when
        nothing is staged."""
        executor = self._executor
        if executor is None:
            return None
        slots = []
        for slot, (sid, seg) in enumerate(executor.pairs):
            total = int(seg.num_docs)
            live = int(seg.live_doc_count)
            slots.append({
                "slot": slot, "shard": int(sid), "segment": seg.name,
                "docs": total, "live": live,
                "tombstone_density": (round(1.0 - live / total, 4)
                                      if total else 0.0),
            })
        free = executor.free_slots()
        return {
            "n_slots": executor.n_slots,
            "slots_per_device": executor.slots_per_dev,
            "free_slots": free,
            "free_slots_per_device": round(free / executor.n_dev, 2),
            "slots": slots,
        }

    def note_compaction_run(self) -> None:
        with self._counter_lock:
            self.compaction_runs_total += 1

    def restage_for_compaction(self) -> bool:
        """Background slot compaction's restage (ISSUE 20): build a
        FRESH generation over the current segment set with fresh slot
        headroom, classified ``compaction`` — merges sparse slots into
        fresh ones and releases the old generation. Off the query path
        (the owner's single-flight pass calls it); ledger-exact through
        the same register-then-commit rebuild as any staging."""
        pairs = self._current_pairs()
        mesh = self._mesh_or_default()
        if (not pairs
                or len(pairs) > mesh.devices.size * max(self.max_slots,
                                                        1)):
            return False
        key = self._key_for(pairs)
        with self._stage_lock:
            if self._executor is None:
                return False  # nothing staged: the next query goes cold
            settings = getattr(self.svc, "settings", None)
            codec = (settings.get_str(
                "index.search.pallas.postings_codec", "default")
                if settings is not None else None)
            return self._stage_rebuild(mesh, pairs, key, codec,
                                       reason="compaction")

    @staticmethod
    def _needs_counts(q) -> bool:
        """Cheap body-level pre-check for the Q==1 pruned fast path:
        queries carrying minimum_should_match / operator clauses are
        likely to need the dense-counts kernel variant, which query_batch
        rejects AFTER building every shard's plan — skipping them here
        avoids paying that planning twice (false positives only cost the
        fast path, never correctness)."""
        if isinstance(q, dict):
            return any(k in ("minimum_should_match", "operator")
                       or IndexMeshSearch._needs_counts(v)
                       for k, v in q.items())
        if isinstance(q, list):
            return any(IndexMeshSearch._needs_counts(v) for v in q)
        return False

    def _pruning_config(self):
        """(enabled, probe_tiles) from the live settings — block-max
        pruned scoring is dynamic (search.pallas.pruning.*): a PUT
        _cluster/settings update lands as per-index overrides (Node's
        update consumers), which win over the index's creation-time
        Settings map (docs/PRUNING.md)."""
        settings = getattr(self.svc, "settings", None)
        enabled = getattr(self.svc, "pruning_enabled_override", None)
        if enabled is None:
            if settings is None:
                enabled = False
            else:
                enabled = settings.get_bool(
                    "search.pallas.pruning.enabled", False)
        # brownout step 1 (ISSUE 12, docs/OVERLOAD.md): under admission-
        # queue pressure the overload plane forces pruned / gte-totals
        # eligibility — cheaper tiles before shedding features — and
        # releases it as the queue drains
        adm = getattr(self.svc, "admission", None)
        if not enabled and adm is not None \
                and adm.brownout_forces_pruning:
            enabled = True
        if settings is None:
            return bool(enabled), 8
        probe = getattr(self.svc, "pruning_probe_override", None)
        if probe is None:
            probe = (settings.get_int(
                "search.pallas.pruning.probe_tiles", 8)
                if settings is not None else 8)
        if probe not in (2, 4, 8, 16, 32):
            probe = 8
        return bool(enabled), probe

    def _fused_aggs_enabled(self) -> bool:
        """search.aggs.fused resolution (docs/AGGS.md): an explicit
        cluster-level override wins (put_cluster_settings syncs it with
        the search.pallas.* explicitness contract), then the index's
        index.search.aggs.fused ("default" follows the node), then the
        seeded node default (on)."""
        override = getattr(self.svc, "aggs_fused_override", None)
        if override is not None:
            return bool(override)
        settings = getattr(self.svc, "settings", None)
        if settings is None:
            return True
        idx = settings.get_str("index.search.aggs.fused", "default")
        if idx in ("true", "false"):
            return idx == "true"
        return settings.get_bool("search.aggs.fused", True)

    def _note_agg_fallback(self, reason: str, n: int = 1) -> None:
        with self._counter_lock:
            self.agg_host_fallback_total += n
            self.agg_host_fallback_by_reason[reason] = \
                self.agg_host_fallback_by_reason.get(reason, 0) + n

    def _resolve_fused_aggs(self, agg_specs, executor):
        """(FusedAggPlan | None, fallback reason | None) for a mesh-
        served query's agg set — all-or-nothing (docs/AGGS.md). A
        terminal doc-value staging fault demotes the AGGS (not the
        query) to the host reduce (reason ``staging_fault``, classified
        inside resolve_fused_aggs around the staging step only): the
        scoring launch proceeds either way."""
        if not self._fused_aggs_enabled():
            return None, "disabled"
        from elasticsearch_tpu.search.fused_aggs import resolve_fused_aggs

        try:
            return resolve_fused_aggs(agg_specs, executor)
        except Exception:  # noqa: BLE001 — defensive: an unexpected
            # RESOLUTION error (not a device fault — those classify as
            # staging_fault inside resolve_fused_aggs) must degrade to
            # the host reduce, visibly labeled as a resolver defect
            # rather than device-fault telemetry
            _plane_logger.warning(
                "[%s] fused-agg resolution raised; aggregations serve "
                "from the host reduce", self.svc.name, exc_info=True)
            return None, "resolve_error"

    def _knn_config(self):
        """(enabled, tile_sub preference) from the live settings —
        search.knn.* is dynamic (same override pattern as pruning: a
        PUT _cluster/settings update lands as per-index overrides that
        win over creation-time Settings; docs/VECTOR.md)."""
        from elasticsearch_tpu.ops.pallas_knn import (
            DEFAULT_KNN_SUB,
            VALID_KNN_SUBS,
        )

        settings = getattr(self.svc, "settings", None)
        enabled = getattr(self.svc, "knn_enabled_override", None)
        if enabled is None:
            enabled = (settings.get_bool("search.knn.enabled", True)
                       if settings is not None else True)
        sub = getattr(self.svc, "knn_tile_sub_override", None)
        if sub is None:
            sub = (settings.get_int("search.knn.tile_sub",
                                    DEFAULT_KNN_SUB)
                   if settings is not None else DEFAULT_KNN_SUB)
        if sub not in VALID_KNN_SUBS:
            sub = DEFAULT_KNN_SUB
        return bool(enabled), int(sub)

    def query_knn(self, spec: dict, k: int, deadline=None,
                  stats=None, tracer=None) -> Optional[dict]:
        """One kNN query on the mesh MXU plane (the Q == 1 form of
        query_knn_batch). Returns {total, refs, max_score, plane} or
        None when ineligible (callers run the host plan-node rung)."""
        out = self.query_knn_batch([spec], [max(k, 1)], deadline=deadline,
                                   stats=[stats], tracers=[tracer])
        return out[0] if out is not None else None

    def query_knn_batch(self, specs: List[dict], ks: List[int],
                        deadline=None,
                        stats: Optional[list] = None,
                        tracers: Optional[list] = None) -> Optional[list]:
        """Cross-query micro-batching on the kNN MXU plane: Q concurrent
        vector queries against ONE dense_vector field scored by ONE
        batched ``knn_score_tiles`` launch inside one shard_map program —
        the embedding matrix streams out of HBM once for the whole batch
        (the q_batch contract the MicroBatcher feeds, exactly like the
        BM25 rung). Returns one {total, refs, max_score, plane} dict per
        member, or None when the batch can't run here. A plane FAULT
        quarantines mesh_pallas exactly ONCE for the whole batch.
        ``stats``: one request-body "stats" groups list per member (the
        per-shard group counters must not depend on which plane served
        the query)."""
        if self.plane_pref not in ("auto", "pallas"):
            return None
        # single-flight admission (ISSUE 10): after a quarantine's
        # cooldown exactly ONE batch probes the plane; peers serve the
        # healthy rung until the probe commits or fails
        adm = self.plane_health.admit("mesh_pallas")
        if not adm:
            self._note("mesh_pallas", "quarantined", len(specs))
            return None
        try:
            return self._query_knn_batch_admitted(specs, ks, deadline,
                                                  stats, tracers)
        finally:
            if adm == "probe":
                # idempotent: a served batch already re-opened the plane
                # (note_success) and a fault re-benched it
                self.plane_health.release_probe("mesh_pallas")

    def _query_knn_batch_admitted(self, specs, ks, deadline, stats,
                                  tracers) -> Optional[list]:
        from elasticsearch_tpu.index.segment import next_pow2
        from elasticsearch_tpu.mapper.field_types import DenseVectorFieldType
        from elasticsearch_tpu.ops import pallas_knn as pkn
        from elasticsearch_tpu.ops import pallas_scoring as psc
        from elasticsearch_tpu.search.service import DocRef
        from elasticsearch_tpu.testing.disruption import (
            on_kernel_launch,
            on_plane_execute,
        )

        from elasticsearch_tpu.search.telemetry import (
            NULL_TRACER,
            QueryTracer,
        )

        if len(self.svc.shards) < 2:
            return None
        enabled, sub_pref = self._knn_config()
        if not enabled:
            self._note("host", "knn_disabled", len(specs))
            return None
        # shared batch tracer: the launch's phase spans are folded into
        # every member tracer at the end (each member waited on them)
        bt = (QueryTracer() if any(getattr(t, "enabled", False)
                                   for t in (tracers or [])) else NULL_TRACER)
        # field uniformity + request validation OUTSIDE the fault-
        # recording try: a malformed spec (unknown field, wrong dims) is
        # a REQUEST error the serial path owns with its own 4xx, never a
        # plane fault to quarantine on (same split as query_batch)
        try:
            fields = {str(spec["field"]) for spec in specs}
            if len(fields) != 1:
                return None
            field = next(iter(fields))
            ft = self.svc.mapper_service.field_type(field)
            if not isinstance(ft, DenseVectorFieldType):
                return None
            for spec in specs:
                qv = spec["query_vector"]
                if (not isinstance(qv, (list, tuple))
                        or len(qv) != ft.dims
                        or any(isinstance(v, bool)
                               or not isinstance(v, (int, float))
                               or not np.isfinite(v) for v in qv)):
                    # incl. NaN/inf: the serial path owns the 400 (a
                    # NaN would poison scores and drive the kernel's
                    # tie-select past the doc range)
                    return None
        except (KeyError, TypeError):
            return None
        if deadline is not None:
            deadline.checkpoint()
        t_stage = bt.start("staging")
        if not self._ensure_staged():
            self._note("host", self.staging_denied_reason
                       or "knn_staging_unavailable", len(specs))
            return None
        executor = self._executor
        if executor is None:
            self._note("host", "knn_staging_unavailable", len(specs))
            return None
        session = executor.ensure_knn(field, ft.dims, ft.similarity)
        if session is None:
            reason = executor.kernel_denied_reason
            self._note("host", reason or "knn_staging_unavailable",
                       len(specs))
            if reason == "staging_fault":
                # a terminal classified staging fault: bench the plane
                # so peers don't re-pay the staging attempt per query
                # (the post-cooldown probe restages — docs/RESILIENCE.md)
                self.plane_health.record_failure("mesh_pallas",
                                                 reason="staging_fault")
            return None
        q_batch = len(specs)
        q_pad = next_pow2(q_batch)
        kk = next_pow2(max(max(ks), 1))
        d_pad = session["d_pad"]
        nd_knn = session["nd_pad"]
        g = psc.tile_geometry(nd_knn,
                              pkn.knn_tile_sub(nd_knn, d_pad, sub_pref))
        qmat = np.zeros((q_pad, d_pad), np.float32)
        for q, spec in enumerate(specs):
            qmat[q] = pkn.normalize_query(
                np.asarray(spec["query_vector"], np.float32),
                ft.similarity, d_pad)
        bt.stop("staging", t_stage)
        from elasticsearch_tpu.common.errors import TaskCancelledException
        from elasticsearch_tpu.search.cancellation import (
            TimeExceededException,
        )

        try:
            on_plane_execute(self.svc.name, "mesh_pallas")
            run = _mesh_knn_program(
                executor.mesh, executor.slots_per_dev,
                q_pad, kk, g.tile_sub, d_pad, nd_knn,
                session["mode"] == "interpret")
            args = (session["emb"], session["scale"], session["mask"],
                    jnp.asarray(qmat))
            if deadline is not None:
                # a first call compiles the program (seconds): honor the
                # deadline before committing to the launch
                deadline.checkpoint()
            on_kernel_launch(self.svc.name, "knn")
            t_kernel = bt.start("kernel")
            with _MESH_EXEC_LOCK:
                outs = run(*args)
                # async dispatch: completion inside the lock
                jax.block_until_ready(outs)
            bt.stop("kernel", t_kernel)
            keys, docs, slots, totals = (np.asarray(o) for o in outs)
        except (PlanStructureMismatch, NotImplementedError):
            self._note("mesh_pallas", "shape_mismatch", q_batch)
            return None  # shape ineligibility: next rung, no penalty
        except (TaskCancelledException, TimeExceededException):
            raise  # PR-4 contract: the caller owns partial/cancel
        except Exception:  # noqa: BLE001 — plane fault, not a shape miss
            _plane_logger.warning(
                "[%s] kNN execution plane [mesh_pallas] failed; "
                "quarantined for %.1fs", self.svc.name,
                self.plane_health.cooldown_s, exc_info=True)
            self.plane_health.record_failure("mesh_pallas")
            self._note("mesh_pallas", "fault", q_batch)
            return None
        # the launch committed: fully re-open the plane (a probe's
        # success ends the quarantine — single-flight contract)
        self.plane_health.note_success("mesh_pallas")
        with self._counter_lock:
            self.query_total += q_batch
            self.pallas_query_total += q_batch
            self.knn_query_total += q_batch
            if q_batch > 1:
                self.batched_launch_total += 1
                self.batched_query_total += q_batch
        self._note("mesh_pallas",
                   "knn_served_batched" if q_batch > 1 else "knn_served",
                   q_batch)
        # the whole batch streams each slot's bf16 embedding matrix once
        launch_adds = {"embedding_bytes_streamed":
                       executor.n_slots * nd_knn * d_pad * 2}
        t_merge = bt.start("merge")
        results = []
        for q in range(q_batch):
            for sid in self.svc.shards:
                self.svc.shards[sid].searcher.note_query(
                    stats[q] if stats is not None else None)
            refs = []
            max_score = None
            for key, slot, d in zip(keys[q][: ks[q]], slots[q][: ks[q]],
                                    docs[q][: ks[q]]):
                if key == -np.inf or d < 0:
                    continue
                sid, seg = executor.pairs[int(slot)]
                score = float(key)
                refs.append(DocRef(sid, seg.name, int(d), score, ()))
                if max_score is None:
                    max_score = score
            results.append({"total": int(totals[q]), "refs": refs,
                            "max_score": max_score,
                            "plane": "mesh_pallas"})
        bt.stop("merge", t_merge)
        tel = getattr(self.svc, "telemetry", None)
        if tel is not None:
            tel.add_counters(launch_adds)
        for q, tr in enumerate(tracers or []):
            if tr is not None and getattr(tr, "enabled", False):
                tr.merge_from(bt)
                tr.annotate("batch_size", q_batch)
                tr.annotate("batch_member_index", q)
                for key, v in launch_adds.items():
                    tr.annotate(key, int(v))
        return results

    def _sort_plan(self, body: dict, executor: "MeshPlanExecutor"):
        """Resolve the request's sort to staged mesh key columns.

        Returns (sort_keys, sort_spec) — sort_keys None for relevance —
        or the string "fallback" when the sort can't run on the mesh."""
        from elasticsearch_tpu.search.service import normalize_sort

        sort_spec = normalize_sort(body.get("sort"))
        if sort_spec is None:
            return None, None
        if len(sort_spec) != 1:
            return "fallback", None
        field, order, missing = sort_spec[0]
        if not isinstance(field, str) or field == "_geo_distance":
            return "fallback", None
        # (a single _score sort never reaches here: normalize_sort
        # collapses it to relevance ranking already)
        if isinstance(missing, dict):
            return "fallback", None
        if isinstance(missing, str) and missing not in ("_last", "_first"):
            return "fallback", None  # host path owns the error shape
        if isinstance(missing, (int, float)) and not isinstance(
                missing, bool):
            # the fill participates in the f32 rank key like any value
            if float(np.float32(missing)) != float(missing):
                return "fallback", None
        keys = executor.ensure_sort_column(field, order, missing)
        if keys is None:
            return "fallback", None
        return keys, sort_spec

    def _search_after_key(self, search_after, sort_spec,
                          sort_keys, executor) -> Optional[float]:
        """Map the request's search_after cursor to the oriented-key
        space of the staged rank column (strictly-after == key < value),
        or None when the cursor can't cut exactly on the mesh."""
        import bisect

        if not isinstance(search_after, (list, tuple)):
            return None
        if len(search_after) != 1:
            return None  # must match the (single-field) sort length
        after = search_after[0]
        big = 3.0e38
        if sort_spec is None:
            # relevance paging: scores strictly below the cursor score
            try:
                v = float(after)
            except (TypeError, ValueError):
                return None
            if float(np.float32(v)) != v:
                return None  # f32 rounding could move the boundary
            return v
        _field, order, missing = sort_spec[0]
        meta = executor.sort_meta.get(sort_keys[0]) or {}
        vocab = meta.get("vocab")
        if vocab is not None:
            if after is None:
                # a null cursor is a missing-value doc's rendered key:
                # anchor at the same fill ensure_sort_column staged
                if missing == "_first":
                    anchor = big if order == "desc" else -big
                else:
                    anchor = -big if order == "desc" else big
            else:
                # anchor the cursor string in global-ordinal space;
                # between-terms strings land at bisect-position - 0.5 so
                # the strict cut stays exact either way
                s = str(after)
                pos = bisect.bisect_left(vocab, s)
                present = pos < len(vocab) and vocab[pos] == s
                anchor = float(pos) if present else pos - 0.5
                if float(np.float32(anchor)) != anchor:
                    return None  # pos-0.5 loses exactness past 2^23
            oriented = anchor if order == "desc" else -anchor
            return float(np.clip(oriented, -big, big))
        if after is None:
            from elasticsearch_tpu.search.service import _missing_fill

            anchor = _missing_fill(missing, order)
        else:
            try:
                anchor = float(after)
            except (TypeError, ValueError):
                return None
            if float(np.float32(anchor)) != anchor:
                return None
        oriented = anchor if order == "desc" else -anchor
        return float(np.clip(oriented, -big, big))

    def query(self, body: dict, k: int, deadline=None, tracer=None):
        """Returns {total, refs, max_score, aggregations,
        terminated_early} or None if ineligible.
        deadline: SearchDeadline — checkpointed between staging steps and
        plane attempts (timeout raises TimeExceededException for the
        caller's partial-result path; cancellation raises
        TaskCancelledException).
        tracer: QueryTracer — phase spans (parse_rewrite / plan_build /
        staging / kernel / merge) recorded against whichever plane ends
        up serving (docs/OBSERVABILITY.md)."""
        from elasticsearch_tpu.search.aggregations import (
            SegmentView,
            parse_aggs,
            run_aggregations,
        )
        from elasticsearch_tpu.search.query_dsl import (
            ShardQueryContext,
            parse_query,
        )
        from elasticsearch_tpu.search.service import (
            _STR_SENTINEL_HIGH,
            _STR_SENTINEL_LOW,
            DocRef,
            _normalize_rescore,
        )

        from elasticsearch_tpu.search.telemetry import NULL_TRACER

        if tracer is None:
            tracer = NULL_TRACER
        body = body or {}
        if any(body.get(key) is not None for key in self.UNSUPPORTED):
            self._note("host", "unsupported_body")
            return None
        if len(self.svc.shards) < 2:
            self._note("host", "single_shard")
            return None  # single shard: host path is already one program
        if any(getattr(self.svc.shards[s].engine, "index_sort", None)
               for s in self.svc.shards):
            self._note("host", "index_sorted")
            return None  # index-sorted early termination beats top-k
        if deadline is not None:
            deadline.checkpoint()
        if not self._ensure_staged():
            self._note("host", self.staging_denied_reason
                       or "staging_unavailable")
            return None
        executor = self._executor
        if executor is None:
            self._note("host", "staging_unavailable")
            return None
        if deadline is not None:
            deadline.checkpoint()  # staging can compile/transfer
        settings = getattr(self.svc, "settings", None)
        if settings is not None:
            # the cooldown is a DYNAMIC index setting: re-read per query
            # so a live settings update takes effect without a restart
            self.plane_health.cooldown_s = settings.get_time(
                "index.search.plane_quarantine.cooldown", 60.0)
        pruning_on, _probe = self._pruning_config()
        if (pruning_on and isinstance(body.get("query"), dict)
                and all(key in self.BATCHABLE_KEYS for key in body)
                and int(body.get("size", 10) if body.get("size")
                        is not None else 10) > 0
                and not self._needs_counts(body.get("query"))
                and self.plane_pref in ("auto", "pallas")
                and self.plane_health.available("mesh_pallas")):
            # block-max pruned single-query fast path (docs/PRUNING.md):
            # a plain relevance-ranked query rides the batched rung's
            # pruned program with Q == 1, skipping tiles whose bound
            # cannot beat the running top-k threshold. Anything needing
            # every tile's dense output (aggs, sort, counts, rescore)
            # fails the key filter above and executes exhaustively.
            out = self.query_batch([body], deadline=deadline,
                                   tracers=[tracer])
            if out is not None:
                r = out[0]
                return {"total": r["total"], "refs": r["refs"],
                        "max_score": r["max_score"], "aggregations": None,
                        "terminated_early": None, "plane": r["plane"],
                        "pruned": r.get("pruned")}
        t_parse = tracer.start("parse_rewrite")
        agg_specs = parse_aggs(body.get("aggs") or body.get("aggregations"))
        sort_keys, sort_spec = self._sort_plan(body, executor)
        if sort_keys == "fallback":
            self._note("host", "sort_ineligible")
            return None
        # fused on-device aggregations (ISSUE 13, docs/AGGS.md): when
        # every spec is fused-eligible the agg reduction rides INSIDE
        # the mesh program (doc-value columns staged per slot, ledger
        # kind doc_values) and the [n_slots, nd1] matched masks never
        # cross to the host; otherwise the previous with_views host
        # reduce serves, counted per fallback reason
        agg_plan = None
        agg_reason = None
        if agg_specs:
            t_aggstage = tracer.start("staging")
            agg_plan, agg_reason = self._resolve_fused_aggs(agg_specs,
                                                            executor)
            tracer.stop("staging", t_aggstage)

        features = set()
        scalars: Dict[str, float] = {}
        min_score = body.get("min_score")
        if min_score is not None:
            ms = float(min_score)
            if float(np.float32(ms)) != ms:
                self._note("host", "feature_ineligible")
                return None  # f32 compare could move the cut boundary
            features.add("min_score")
            scalars["min_score"] = ms
        slice_col = None
        slice_spec = body.get("slice")
        if slice_spec is not None:
            if (not isinstance(slice_spec, dict)
                    or "id" not in slice_spec or "max" not in slice_spec):
                return None  # host path owns the error shape
            try:
                slice_col = executor.ensure_slice_column(
                    slice_spec, [sid for sid, _seg in executor.pairs],
                    len(self.svc.shards))
            except Exception:  # noqa: BLE001 — host path owns errors
                return None
            if slice_col is None:
                return None
        search_after = body.get("search_after")
        if search_after is not None:
            after_key = self._search_after_key(search_after, sort_spec,
                                               sort_keys, executor)
            if after_key is None:
                self._note("host", "feature_ineligible")
                return None
            features.add("search_after")
            scalars["search_after"] = after_key
        terminate_after = body.get("terminate_after")
        rescore_static = None
        rs_qb = None
        rescore_specs = _normalize_rescore(body.get("rescore"))
        if rescore_specs and sort_spec is None:
            if len(rescore_specs) != 1:
                self._note("host", "feature_ineligible")
                return None  # chained rescorers: host path
            spec = rescore_specs[0]
            rescore_static = (spec["window_size"], spec["score_mode"])
            scalars["query_weight"] = spec["query_weight"]
            scalars["rescore_query_weight"] = spec["rescore_query_weight"]
            rs_qb = parse_query(spec["rescore_query"])
        # (rescore with an explicit sort is a no-op on the host path too)

        qb = parse_query(body.get("query"))
        pf_qb = (parse_query(body["post_filter"])
                 if body.get("post_filter") else None)
        tracer.stop("parse_rewrite", t_parse)
        # plane ladder: try the tile-kernel plane first (one fast plane
        # for distributed queries — the reference runs the same BulkScorer
        # hot loop on every shard), falling back to the scatter mesh when
        # the kernel can't serve this query shape, then to the host path.
        # A plane under quarantine (plane_health) is skipped outright —
        # its last failure already paid the cost — and probed again once
        # the cooldown elapses.
        from elasticsearch_tpu.common.errors import TaskCancelledException
        from elasticsearch_tpu.search.cancellation import (
            TimeExceededException,
        )
        from elasticsearch_tpu.testing.disruption import (
            on_kernel_launch,
            on_plane_execute,
        )

        # single-flight admission per plane (ISSUE 10): "open" attempts
        # freely; "probe" is the one post-cooldown trial whose admission
        # must be handed back if it bails without executing; "" skips
        admissions: Dict[str, str] = {}
        kernel_session = None
        if self.plane_pref in ("auto", "pallas"):
            admissions["mesh_pallas"] = self.plane_health.admit(
                "mesh_pallas")
            if admissions["mesh_pallas"]:
                kernel_session = executor.ensure_kernel()
                if (kernel_session is None
                        and executor.kernel_denied_reason):
                    # HBM budget / staging fault turned the kernel
                    # staging away: the ladder's next rung serves
                    # (docs/OBSERVABILITY.md)
                    reason = executor.kernel_denied_reason
                    self._note("mesh_pallas", reason)
                    if reason == "staging_fault":
                        self.plane_health.record_failure(
                            "mesh_pallas", reason="staging_fault")
            else:
                self._note("mesh_pallas", "quarantined")
        attempts = []
        if kernel_session is not None:
            attempts.append(("mesh_pallas", kernel_session))
        if self.plane_pref != "pallas":
            admissions["mesh"] = self.plane_health.admit("mesh")
            if admissions["mesh"]:
                # plane=pallas pins "kernel or host": when the kernel is
                # unavailable OR quarantined, the ladder's next rung is
                # the host path, never the scatter mesh the operator
                # excluded
                attempts.append(("mesh", None))
        outs = None
        used_pallas = False
        try:
            for plane, session in attempts:
                if deadline is not None:
                    deadline.checkpoint()
                try:
                    on_plane_execute(self.svc.name, plane)
                    t_plan = tracer.start("plan_build")
                    plans = []
                    pf_plans = [] if pf_qb is not None else None
                    rs_plans = [] if rs_qb is not None else None
                    ctxs = {}
                    for sid, seg in executor.pairs:
                        shard = self.svc.shards[sid]
                        ctx = ShardQueryContext(shard.mapper_service,
                                                engine=shard.engine)
                        # mesh plans must stack across shards: scorer
                        # nodes keep one skeleton on every shard, and
                        # kernel nodes defer table geometry to
                        # harmonization below
                        ctx.for_mesh = True
                        ctx.mesh_kernel = session
                        ctxs[sid] = ctx
                        plans.append(qb.to_plan(ctx, seg))
                        # post_filter/rescore plans stay on scatter
                        # nodes: they gate/adjust, the main scorer is
                        # the hot loop
                        ctx.mesh_kernel = None
                        if pf_qb is not None:
                            pf_plans.append(pf_qb.to_plan(ctx, seg))
                        if rs_qb is not None:
                            rs_plans.append(rs_qb.to_plan(ctx, seg))
                    used_pallas = False
                    if session is not None:
                        used_pallas = executor.harmonize_kernel_nodes(
                            plans) > 0
                    tracer.stop("plan_build", t_plan)
                    on_kernel_launch(self.svc.name, plane)
                    outs = executor.execute(
                        plans, k, sort_keys=sort_keys,
                        with_views=bool(agg_specs) and agg_plan is None,
                        pf_plans=pf_plans,
                        rs_plans=rs_plans, scalars=scalars,
                        features=frozenset(features), slice_col=slice_col,
                        rescore_static=rescore_static, tracer=tracer,
                        agg_static=(agg_plan.statics
                                    if agg_plan is not None else ()))
                    # the plane served: fully re-open it (ends a probe's
                    # quarantine — single-flight contract)
                    self.plane_health.note_success(plane)
                    break
                except (PlanStructureMismatch, NotImplementedError):
                    self._note(plane, "shape_mismatch")
                    continue  # shape ineligibility: next plane (no penalty)
                except (TaskCancelledException, TimeExceededException):
                    raise
                except Exception:  # noqa: BLE001 — plane fault, not a
                    # shape miss: compile error / device OOM / runtime
                    # fault (or injected scheme) — bench the plane for
                    # the cooldown and serve from the next rung
                    _plane_logger.warning(
                        "[%s] execution plane [%s] failed; quarantined "
                        "for %.1fs", self.svc.name, plane,
                        self.plane_health.cooldown_s, exc_info=True)
                    self.plane_health.record_failure(plane)
                    self._note(plane, "fault")
                    continue
        finally:
            # any probe admission not consumed by note_success /
            # record_failure (shape fallback, deadline, early bail)
            # hands its single-flight slot back — idempotent after
            # either of those
            for plane, adm in admissions.items():
                if adm == "probe":
                    self.plane_health.release_probe(plane)
        if outs is None:
            self._note("host", "no_mesh_plane")
            return None
        t_merge = tracer.start("merge")
        keys, slots, docs, total, scores, raws, seg_counts = outs[:7]
        keys = np.asarray(keys)
        scores = np.asarray(scores)
        raws = np.asarray(raws)
        total = int(total)
        # terminate_after caps per SHARD (each shard's collector stops
        # after N docs) while a mesh device holds one SEGMENT: group the
        # per-device counts by shard before capping — host-path contract
        # (search/service.py query(): cap reported total, set the flag)
        terminated_early = None
        if terminate_after:
            ta = int(terminate_after)
            counts = np.asarray(seg_counts)
            by_shard: Dict[int, int] = {}
            for i, (sid, _seg) in enumerate(executor.pairs):
                by_shard[sid] = by_shard.get(sid, 0) + int(counts[i])
            total = sum(min(c, ta) for c in by_shard.values())
            terminated_early = any(c >= ta for c in by_shard.values())
        with self._counter_lock:
            self.query_total += 1
            if used_pallas:
                self.pallas_query_total += 1
        self._note("mesh_pallas" if used_pallas else "mesh", "served")
        # per-shard search stats stay attributed even though the mesh
        # executes all shards as one program (SearchStats semantics)
        for sid in self.svc.shards:
            self.svc.shards[sid].searcher.note_query(body.get("stats"))
        vocab = None
        if sort_keys is not None:
            vocab = (executor.sort_meta.get(sort_keys[0])
                     or {}).get("vocab")
        refs = []
        max_score = None
        for i, (key, slot, d) in enumerate(zip(keys, np.asarray(slots),
                                               np.asarray(docs))):
            if key == -np.inf:
                continue
            sid, seg = executor.pairs[int(slot)]
            score = float(scores[i])
            if sort_keys is None:
                sv = (score,) if rescore_static is not None else ()
            elif vocab is not None:
                # global ordinal back to the term; missing-fill
                # sentinels render as the host path's string sentinels
                # (both serialize to null)
                raw = float(raws[i])
                if abs(raw) >= 3.0e38:
                    sv = (_STR_SENTINEL_HIGH if raw > 0
                          else _STR_SENTINEL_LOW,)
                else:
                    sv = (vocab[int(round(raw))],)
            else:
                # missing-fill sentinels surface as +/-inf, which
                # fetch_hits renders as null (same as the host path)
                raw = float(raws[i])
                if abs(raw) >= 3.0e38:
                    raw = np.inf if raw > 0 else -np.inf
                sv = (raw,)
            refs.append(DocRef(sid, seg.name, int(d), score, sv))
            if max_score is None and sort_spec is None:
                max_score = score
        tracer.stop("merge", t_merge)
        aggregations = None
        if agg_specs:
            t_agg = tracer.start("aggregate")
            if agg_plan is not None:
                from elasticsearch_tpu.search.fused_aggs import (
                    finalize_fused,
                )

                agg_outs = [np.asarray(o) for o in outs[7:]]
                aggregations = finalize_fused(agg_plan, agg_outs,
                                              len(executor.pairs))
                with self._counter_lock:
                    self.agg_fused_query_total += 1
                tel = getattr(self.svc, "telemetry", None)
                if tel is not None:
                    # doc-value column bytes the fused launch read in
                    # place of the host round-trip (docs/AGGS.md)
                    tel.add_counters({
                        "doc_values_bytes_streamed":
                            agg_plan.staged_bytes(executor._seg_staged)})
            else:
                matched_np = np.asarray(outs[7])
                scores_np = np.asarray(outs[8])
                views = []
                for i, (sid, seg) in enumerate(executor.pairs):
                    nd1 = seg.nd_pad + 1
                    views.append(SegmentView(
                        seg, matched_np[i, :nd1], ctxs[sid],
                        scores_np[i, :nd1]))
                aggregations = run_aggregations(agg_specs, views)
                self._note_agg_fallback(agg_reason or "field_ineligible")
            tracer.stop("aggregate", t_agg)
        return {"total": total, "refs": refs, "max_score": max_score,
                "aggregations": aggregations,
                "terminated_early": terminated_early,
                # which scoring engine the mesh program ran — surfaced as
                # the response's _plane marker and the planes counters
                "plane": "mesh_pallas" if used_pallas else "mesh"}

    # request keys the BATCHED mesh_pallas program covers: plain
    # relevance-ranked queries (the high-QPS traffic shape the batching
    # exists for). Anything richer falls to the host-batched rung, whose
    # per-query pipeline covers the full request surface.
    # ("profile" rides along: a profiled member executes identically —
    # byte-identical hits — and additionally reports its phase spans)
    BATCHABLE_KEYS = frozenset({
        "query", "size", "from", "timeout",
        "allow_partial_search_results", "stats", "profile",
    })

    def query_batch(self, bodies: List[dict],
                    deadline=None,
                    tracers: Optional[list] = None) -> Optional[list]:
        """Cross-query micro-batching on the mesh_pallas rung: Q
        concurrent queries scored by ONE batched kernel launch inside
        one shard_map program (per-tile DMA windows fetched once for the
        whole batch — see ops/pallas_scoring.score_tiles q_batch).

        Returns one {total, refs, max_score, plane} dict per member, or
        None when the batch can't run here (callers fall to the
        host-batched rung). A plane FAULT quarantines mesh_pallas
        exactly ONCE for the whole batch — not Q times.

        deadline: SearchDeadline of the SINGLE-query pruned fast path
        (IndexMeshSearch.query routes through here with Q == 1) —
        checkpointed before table building and before the launch, same
        contract as the serial ladder. Batch callers (search_batch)
        handle per-member deadlines themselves and pass None."""
        if self.plane_pref not in ("auto", "pallas"):
            return None
        # single-flight admission (ISSUE 10): after cooldown exactly
        # ONE batch probes the benched plane; peers serve the next rung
        adm = self.plane_health.admit("mesh_pallas")
        if not adm:
            self._note("mesh_pallas", "quarantined", len(bodies))
            return None
        try:
            return self._query_batch_admitted(bodies, deadline, tracers)
        finally:
            if adm == "probe":
                self.plane_health.release_probe("mesh_pallas")

    def _query_batch_admitted(self, bodies, deadline,
                              tracers) -> Optional[list]:
        from elasticsearch_tpu.index.segment import next_pow2
        from elasticsearch_tpu.ops import pallas_scoring as psc
        from elasticsearch_tpu.search.plan import PallasScoreTermsNode
        from elasticsearch_tpu.search.query_dsl import (
            ShardQueryContext,
            parse_query,
        )
        from elasticsearch_tpu.search.service import DocRef
        from elasticsearch_tpu.search.telemetry import (
            NULL_TRACER,
            QueryTracer,
        )
        from elasticsearch_tpu.testing.disruption import (
            on_kernel_launch,
            on_plane_execute,
        )

        if len(self.svc.shards) < 2:
            return None
        for body in bodies:
            body = body or {}
            if not isinstance(body.get("query"), dict):
                return None
            # agg bodies no longer fail the key filter (ISSUE 13): an
            # agg-carrying member rides the batched DENSE program when
            # its whole agg set is fused-eligible (resolved below)
            if any(key not in self.BATCHABLE_KEYS
                   and key not in ("aggs", "aggregations")
                   for key in body):
                return None
        if any(getattr(self.svc.shards[s].engine, "index_sort", None)
               for s in self.svc.shards):
            return None
        # shared batch tracer: one set of launch-phase spans, folded into
        # every member's tracer below (they all waited on the launch)
        bt = (QueryTracer() if any(getattr(t, "enabled", False)
                                   for t in (tracers or [])) else NULL_TRACER)
        t_stage0 = bt.start("staging")
        if not self._ensure_staged():
            self._note("host", self.staging_denied_reason
                       or "staging_unavailable", len(bodies))
            return None
        executor = self._executor
        if executor is None:
            self._note("host", "staging_unavailable", len(bodies))
            return None
        session = executor.ensure_kernel()
        bt.stop("staging", t_stage0)
        if session is None:
            reason = executor.kernel_denied_reason
            self._note("host", reason or "staging_unavailable",
                       len(bodies))
            if reason == "staging_fault":
                # terminal classified staging fault: quarantine so the
                # next queries skip straight to the healthy rung and the
                # post-cooldown probe restages (docs/RESILIENCE.md)
                self.plane_health.record_failure("mesh_pallas",
                                                 reason="staging_fault")
            return None
        q_batch = len(bodies)
        ks = []
        for body in bodies:
            from_ = int(body.get("from", 0) or 0)
            size = (int(body.get("size"))
                    if body.get("size") is not None else 10)
            ks.append(max(from_ + size, 1))
        # bucket the compiled-program key: batch size is set by arrival
        # timing (2..max_queries) and kk by the members' size params, so
        # raw values would compile a fresh shard_map+kernel program per
        # combination. Pad q_batch to the next power of two (extra weight
        # rows are all-zero = dead queries) and kk likewise — at most
        # ~4x4 program variants instead of one per traffic pattern.
        kk = next_pow2(max(ks))
        q_pad = next_pow2(q_batch)
        geom = session["geom"]
        n_pairs = len(executor.pairs)
        # per-member, per-slot kernel lane sets via the same deferred
        # plan builder the serial mesh path uses — the plan must be
        # EXACTLY one kernel-scored disjunction (no wrapper nodes).
        # Built OUTSIDE the fault-recording try: a malformed member body
        # (parse/mapping error) is a REQUEST error the serial path owns
        # with its own 4xx, never a plane fault to quarantine on — same
        # split as the serial ladder, which parses before its attempts.
        t_plan = bt.start("plan_build")
        try:
            lane_sets = [[None] * q_batch for _ in range(n_pairs)]
            for q, body in enumerate(bodies):
                qb = parse_query(body.get("query"))
                for slot, (sid, seg) in enumerate(executor.pairs):
                    shard = self.svc.shards[sid]
                    ctx = ShardQueryContext(shard.mapper_service,
                                            engine=shard.engine)
                    ctx.for_mesh = True
                    ctx.mesh_kernel = session
                    plan = qb.to_plan(ctx, seg)
                    if (not isinstance(plan, PallasScoreTermsNode)
                            or plan._mesh_lanes is None
                            or plan.with_counts):
                        # minimum_should_match > 1 needs the dense-counts
                        # variant the fused top-k kernel doesn't emit
                        return None
                    lane_sets[slot][q] = plan._mesh_lanes
        except Exception:  # noqa: BLE001 — request-shaped error: serial
            # execution surfaces it per member with the right status
            return None
        bt.stop("plan_build", t_plan)
        # fused aggs for batched members (ISSUE 13, docs/AGGS.md):
        # ALL-or-nothing per batch — if any agg'd member's set is not
        # fused-eligible the whole batch falls to the host rung (whose
        # per-member pipeline owns the full agg surface); heterogeneous
        # eligible bodies each reduce their own specs in the shared
        # dense launch (member isolation)
        member_agg_plans = [None] * q_batch
        agg_members = [bool((b or {}).get("aggs")
                            or (b or {}).get("aggregations"))
                       for b in bodies]
        if any(agg_members):
            if not self._fused_aggs_enabled():
                self._note_agg_fallback("disabled", sum(agg_members))
                return None
            from elasticsearch_tpu.search.aggregations import parse_aggs

            t_aggstage = bt.start("staging")
            try:
                for q, body in enumerate(bodies):
                    if not agg_members[q]:
                        continue
                    body = body or {}
                    try:
                        specs = parse_aggs(body.get("aggs")
                                           or body.get("aggregations"))
                    except Exception:  # noqa: BLE001 — request error:
                        # serial execution surfaces the member's 400
                        return None
                    plan, reason = self._resolve_fused_aggs(specs,
                                                            executor)
                    if plan is None:
                        self._note_agg_fallback(
                            reason or "field_ineligible")
                        return None
                    member_agg_plans[q] = plan
            finally:
                bt.stop("staging", t_aggstage)
        has_aggs = any(p is not None for p in member_agg_plans)
        pruning, probe = self._pruning_config()
        if has_aggs:
            # pruning x aggs mutual exclusion (docs/PRUNING.md): WAND-
            # skipped tiles would corrupt buckets — agg batches always
            # run the exhaustive dense formulation
            pruning = False
        if pruning and any(
                int((b or {}).get("size", 10)
                    if (b or {}).get("size") is not None else 10) <= 0
                for b in bodies):
            # a size:0 member is a total/count-only consumer (_count,
            # agg-less counts): exact totals are the contract
            # (docs/PRUNING.md), so the batch runs exhaustively
            pruning = False
        codec = session.get("codec", "raw")
        pruned_stats = None
        from elasticsearch_tpu.common.errors import TaskCancelledException
        from elasticsearch_tpu.search.cancellation import (
            TimeExceededException,
        )

        if deadline is not None:
            deadline.checkpoint()
        try:
            on_plane_execute(self.svc.name, "mesh_pallas")
            t_stage = bt.start("staging")
            # shared batched tables: per-slot unions on ONE collective
            # geometry (a dense union on ANY slot shrinks everyone's
            # tile); build_tile_tables_batched owns the union/pad
            # contract — same code the host rung runs
            unions = [psc.union_query_lanes(lane_sets[slot])[0]
                      for slot in range(n_pairs)]
            t_pad = max(next_pow2(max(len(u), 1)) for u in unions)
            sub = geom.tile_sub
            if pruning:
                # pruning wants enough tiles to split probe/rest: shrink
                # the tile until the doc space yields at least 2*probe
                # tiles (the 1M bench corpus already has 64 at the
                # default tile — only small corpora shrink). Floor the
                # shrink at sub=8 on real hardware (mosaic sublane
                # granularity; interpret mode has no such constraint),
                # and if even the floor can't yield enough tiles, keep
                # the ORIGINAL geometry and run exhaustively — the
                # ladder's geometry must never degrade for a pruning
                # attempt that then doesn't happen.
                sub_floor = 1 if session["mode"] == "interpret" else 8
                sub_p = sub
                while (sub_p > sub_floor and psc.tile_geometry(
                        geom.nd_pad, sub_p).n_tiles < 2 * probe):
                    sub_p //= 2
                if psc.tile_geometry(geom.nd_pad,
                                     sub_p).n_tiles >= 2 * probe:
                    sub = sub_p
                else:
                    pruning = False  # corpus too small to prune here
            while True:
                g = geom if sub == geom.tile_sub else psc.tile_geometry(
                    geom.nd_pad, sub)
                try:
                    tables = []
                    for slot, (sid, seg) in enumerate(executor.pairs):
                        bmin, bmax = session["meta"][id(seg)][:2]
                        tables.append(psc.build_tile_tables_batched(
                            lane_sets[slot], bmin, bmax, g, t_pad=t_pad))
                    break
                except ValueError:
                    if sub <= 32 or g.tile_sub < sub:
                        return None  # no shared geometry: host rung
                    sub //= 2
            cb = max(t[3] for t in tables)
            live_key = ("k_live_t" if g.tile_sub == geom.tile_sub
                        else executor.ensure_kernel_live(g.tile_sub))
            n_slots = executor.n_slots
            n_tiles = tables[0][0].shape[0]
            rl = np.zeros((n_slots, n_tiles, t_pad), np.int32)
            rh = np.zeros((n_slots, n_tiles, t_pad), np.int32)
            w_all = np.zeros((n_slots, q_pad, t_pad), np.float32)
            for slot in range(n_pairs):
                rl[slot] = tables[slot][0]
                rh[slot] = tables[slot][1]
                w_all[slot, : q_batch] = tables[slot][2]
            # filler slots/queries keep zero tables/weights: their live
            # masks are all-dead and zero weights score nothing
            tps = psc.tiles_per_step_default()
            sharding = executor._sharding
            staged = executor._seg_staged
            corpus = ((staged["k_packed"],) if codec == "packed"
                      else (staged["k_docs"], staged["k_frac"]))
            plans_p = None
            if pruning and n_tiles > probe:
                # per-slot block-max pruning plans (host side: order
                # tiles by bound, split probe/rest) — the threshold
                # exchange itself stays on-device in the program
                plans_p = []
                for slot in range(n_pairs):
                    seg = executor.pairs[slot][1]
                    bfmax = session["meta"][id(seg)][2]
                    ub = executor.tile_lane_ub_cached(
                        seg, unions[slot], rl[slot], rh[slot], bfmax,
                        g.tile_sub)
                    plan = psc.plan_pruned_tiles(
                        rl[slot], rh[slot], w_all[slot], bfmax, probe,
                        ub=ub)
                    if plan is None:
                        plans_p = None
                        break
                    plans_p.append(plan)
            if plans_p is not None:
                n_rest = n_tiles - probe
                rl_p = np.zeros((n_slots, probe, t_pad), np.int32)
                rh_p = np.zeros((n_slots, probe, t_pad), np.int32)
                tid_p = np.zeros((n_slots, probe), np.int32)
                rl_r = np.zeros((n_slots, n_rest, t_pad), np.int32)
                rh_r = np.zeros((n_slots, n_rest, t_pad), np.int32)
                tid_r = np.zeros((n_slots, n_rest), np.int32)
                bounds_r = np.full((n_slots, n_rest, q_pad), -np.inf,
                                   np.float32)
                for slot, plan in enumerate(plans_p):
                    rl_p[slot] = plan["rl_probe"]
                    rh_p[slot] = plan["rh_probe"]
                    tid_p[slot] = plan["tid_probe"]
                    rl_r[slot] = plan["rl_rest"]
                    rh_r[slot] = plan["rh_rest"]
                    tid_r[slot] = plan["tid_rest"]
                    bounds_r[slot] = plan["bounds_rest"]
                run = _mesh_batched_pruned_program(
                    executor.mesh, executor.slots_per_dev,
                    q_pad, kk, t_pad, cb, g.tile_sub, tps,
                    session["mode"] == "interpret", codec, probe, n_rest)
                slot_real = np.zeros(n_slots, np.int32)
                slot_real[:n_pairs] = 1
                args = corpus + (
                    staged[live_key],
                    jax.device_put(rl_p, sharding),
                    jax.device_put(rh_p, sharding),
                    jax.device_put(tid_p, sharding),
                    jax.device_put(rl_r, sharding),
                    jax.device_put(rh_r, sharding),
                    jax.device_put(tid_r, sharding),
                    jax.device_put(bounds_r, sharding),
                    jax.device_put(w_all, sharding),
                    jax.device_put(slot_real, sharding),
                    jnp.int32(q_batch))
                bt.stop("staging", t_stage)
                if deadline is not None:
                    # a first call compiles the pruned program (seconds):
                    # honor the deadline before committing to the launch
                    deadline.checkpoint()
                on_kernel_launch(self.svc.name, "pruned")
                t_kernel = bt.start("kernel")
                with _MESH_EXEC_LOCK:
                    outs = run(*args)
                    jax.block_until_ready(outs)
                bt.stop("kernel", t_kernel)
                keys, docs, slots, totals, scored, tiles_total = (
                    np.asarray(o) for o in outs)
                pruned_stats = {
                    "tiles_scored": int(scored),
                    "tiles_pruned": int(tiles_total) - int(scored),
                }
                # DMA economy of this launch: every scored tile streams
                # t_pad cb-block posting windows; pruned tiles skip them
                wb = 4 if codec == "packed" else 8
                tile_bytes = t_pad * cb * psc.LANE * wb
                launch_adds = {
                    "postings_bytes_streamed":
                        pruned_stats["tiles_scored"] * tile_bytes,
                    "postings_bytes_skipped":
                        pruned_stats["tiles_pruned"] * tile_bytes,
                    "tiles_scored": pruned_stats["tiles_scored"],
                    "tiles_pruned": pruned_stats["tiles_pruned"],
                }
            elif has_aggs:
                # agg-carrying batch: ONE dense launch both ranks and
                # aggregates — the posting windows and the doc-value
                # columns stream once for the whole burst, the matched
                # masks reduce on device (ISSUE 13, docs/AGGS.md)
                agg_statics = tuple(
                    (member_agg_plans[q].statics
                     if q < q_batch and member_agg_plans[q] is not None
                     else ())
                    for q in range(q_pad))
                agg_keys = sorted({key for p in member_agg_plans
                                   if p is not None
                                   for key in p.column_keys()})
                agg_cols = {key: staged[key] for key in agg_keys}
                run = _mesh_batched_dense_agg_program(
                    executor.mesh, executor.slots_per_dev,
                    q_pad, kk, t_pad, cb, g.tile_sub, tps,
                    session["mode"] == "interpret", codec,
                    agg_statics, executor.nd1)
                args = corpus + (staged[live_key],
                                 jax.device_put(rl, sharding),
                                 jax.device_put(rh, sharding),
                                 jax.device_put(w_all, sharding),
                                 agg_cols)
                bt.stop("staging", t_stage)
                if deadline is not None:
                    deadline.checkpoint()
                on_kernel_launch(self.svc.name, "batched")
                t_kernel = bt.start("kernel")
                with _MESH_EXEC_LOCK:
                    outs = run(*args)
                    jax.block_until_ready(outs)
                bt.stop("kernel", t_kernel)
                keys, docs, slots, totals = (np.asarray(o)
                                             for o in outs[:4])
                agg_raw = [np.asarray(o) for o in outs[4:]]
                wb = 4 if codec == "packed" else 8
                launch_adds = {
                    "postings_bytes_streamed":
                        n_tiles * n_pairs * t_pad * cb * psc.LANE * wb,
                    "doc_values_bytes_streamed":
                        sum(int(staged[key].nbytes) for key in agg_keys),
                }
            else:
                run = _mesh_batched_kernel_program(
                    executor.mesh, executor.slots_per_dev,
                    q_pad, kk, t_pad, cb, g.tile_sub, tps,
                    session["mode"] == "interpret", codec)
                args = corpus + (staged[live_key],
                                 jax.device_put(rl, sharding),
                                 jax.device_put(rh, sharding),
                                 jax.device_put(w_all, sharding))
                bt.stop("staging", t_stage)
                if deadline is not None:
                    deadline.checkpoint()
                on_kernel_launch(self.svc.name, "batched")
                t_kernel = bt.start("kernel")
                with _MESH_EXEC_LOCK:
                    outs = run(*args)
                    # async dispatch: completion inside the lock (above)
                    jax.block_until_ready(outs)
                bt.stop("kernel", t_kernel)
                keys, docs, slots, totals = (np.asarray(o) for o in outs)
                wb = 4 if codec == "packed" else 8
                launch_adds = {
                    "postings_bytes_streamed":
                        n_tiles * n_pairs * t_pad * cb * psc.LANE * wb,
                }
        except (PlanStructureMismatch, NotImplementedError):
            self._note("mesh_pallas", "shape_mismatch", q_batch)
            return None  # shape ineligibility: next rung, no penalty
        except (TaskCancelledException, TimeExceededException):
            # deadline/cancel tripped a checkpoint (single-query fast
            # path): the PR-4 contract — partial/timed_out or a clean
            # cancellation error — belongs to the caller, never a
            # quarantine
            raise
        except Exception:  # noqa: BLE001 — plane fault, not a shape miss
            # batch-wide fault: bench the plane ONCE (not Q times) and
            # let the caller serve the members from the next rung
            _plane_logger.warning(
                "[%s] batched execution plane [mesh_pallas] failed; "
                "quarantined for %.1fs", self.svc.name,
                self.plane_health.cooldown_s, exc_info=True)
            self.plane_health.record_failure("mesh_pallas")
            self._note("mesh_pallas", "fault", q_batch)
            return None
        # the launch committed: fully re-open the plane (a probe's
        # success ends the quarantine — single-flight contract)
        self.plane_health.note_success("mesh_pallas")
        with self._counter_lock:
            self.query_total += q_batch
            self.pallas_query_total += q_batch
            if q_batch > 1:
                # the Q==1 pruned fast path is not cross-query batching:
                # it must not inflate the batching-adoption telemetry
                # (docs/BATCHING.md counts launch-SHARING members only)
                self.batched_launch_total += 1
                self.batched_query_total += q_batch
            if pruned_stats is not None:
                self.pruned_query_total += q_batch
                self.tiles_scored_total += pruned_stats["tiles_scored"]
                self.tiles_pruned_total += pruned_stats["tiles_pruned"]
        self._note("mesh_pallas",
                   "served_batched" if q_batch > 1 else
                   ("served_pruned" if pruned_stats is not None
                    else "served"), q_batch)
        member_aggs = [None] * q_batch
        if has_aggs:
            from elasticsearch_tpu.search.fused_aggs import (
                finalize_fused,
                n_agg_outputs,
            )

            t_aggf = bt.start("aggregate")
            pos = 0
            for q in range(q_batch):
                plan = member_agg_plans[q]
                if plan is None:
                    continue
                n = n_agg_outputs(plan.statics)
                member_aggs[q] = finalize_fused(
                    plan, agg_raw[pos: pos + n], n_pairs)
                pos += n
            bt.stop("aggregate", t_aggf)
            with self._counter_lock:
                self.agg_fused_query_total += sum(
                    1 for p in member_agg_plans if p is not None)
        t_merge = bt.start("merge")
        results = []
        for q, body in enumerate(bodies):
            # per-shard search stats stay attributed per MEMBER (the
            # batch is an execution detail, not a stats unit)
            for sid in self.svc.shards:
                self.svc.shards[sid].searcher.note_query(
                    (body or {}).get("stats"))
            refs = []
            max_score = None
            for key, slot, d in zip(keys[q][: ks[q]], slots[q][: ks[q]],
                                    docs[q][: ks[q]]):
                if key == -np.inf or d < 0:
                    continue
                sid, seg = executor.pairs[int(slot)]
                score = float(key)
                refs.append(DocRef(sid, seg.name, int(d), score, ()))
                if max_score is None:
                    max_score = score
            result = {"total": int(totals[q]), "refs": refs,
                      "max_score": max_score, "plane": "mesh_pallas"}
            if member_aggs[q] is not None:
                result["aggregations"] = member_aggs[q]
            if pruned_stats is not None:
                # per-query debug marker (the response's _pruned field):
                # under pruning `total` counts matches in SCORED tiles
                # only — a documented lower bound, which the marker's
                # total_relation records (WAND semantics, docs/PRUNING.md;
                # the ES6 response shape keeps hits.total a bare int)
                result["pruned"] = dict(pruned_stats,
                                        total_relation="gte")
            results.append(result)
        bt.stop("merge", t_merge)
        # launch-level byte/tile totals fold into the registry ONCE (a
        # batch must not multiply them); members see them as profile
        # annotations of the launch they shared
        tel = getattr(self.svc, "telemetry", None)
        if tel is not None:
            tel.add_counters(launch_adds)
        for q, tr in enumerate(tracers or []):
            if tr is not None and getattr(tr, "enabled", False):
                tr.merge_from(bt)
                tr.annotate("batch_size", q_batch)
                tr.annotate("batch_member_index", q)
                for key, v in launch_adds.items():
                    tr.annotate(key, int(v))
        return results


class MeshPlanExecutor:
    """Stage N sealed segments onto a device mesh once; run any query
    plan as one compiled multi-device program.

    Segments PACK: with more segments than devices, each device owns
    ``slots_per_dev = ceil(N / n_dev)`` slots in the stacked leading axis
    and the per-device program unrolls its slots (per-slot live masks keep
    padding slots dead) — a realistically-refreshed index (many NRT
    segments per shard) stays on the mesh plane instead of silently
    falling back to the host path."""

    _SCOPE_SEQ = itertools.count(1)

    def __init__(self, segments: List, mesh: Optional[Mesh] = None,
                 postings_codec: Optional[str] = None,
                 index_name: Optional[str] = None,
                 stage_reason: str = "initial",
                 slots_per_dev: Optional[int] = None):
        from elasticsearch_tpu.parallel.distributed import stack_shard_arrays
        from elasticsearch_tpu.parallel.mesh import shard_mesh

        self.mesh = mesh or shard_mesh()
        self.n_dev = self.mesh.devices.size
        self.segments = segments
        # device-memory accountant identity (ISSUE 9): one LRU scope per
        # executor generation; every rebuild is a fresh scope so the old
        # generation's release is exact (next() is atomic — concurrent
        # first-queries must never share a scope id)
        self.index_name = index_name or "_unassigned"
        self.scope = f"mesh#{next(self._SCOPE_SEQ)}"
        # (shard_id, segment) per slot — owned by THIS generation so a
        # query that pinned an executor never reads a concurrently
        # restaged pair list (IndexMeshSearch._ensure_staged overwrites
        # with the real shard ids; the positional default serves direct
        # constructions in tests/bench)
        self.pairs: List[Tuple[int, object]] = list(enumerate(segments))
        # armed by the owner via make_evictable AFTER install — a
        # generation under construction is deliberately not evictable
        self._evict_cb = None
        # why this generation staged (initial / refresh /
        # delete_invalidation / geometry_change) — every table this
        # executor stages inherits it
        self._stage_reason = stage_reason
        # postings codec preference for the kernel-plane staging
        # (index.search.pallas.postings_codec; resolved against the doc
        # space at ensure_kernel time — docs/PRUNING.md)
        self.postings_codec_pref = postings_codec
        # staged posting bytes + effective codec, exported via _stats
        self.postings_bytes_staged = 0
        self.postings_codec = "raw"
        # slot-allocator headroom (ISSUE 20): the owner may hint MORE
        # slots per device than the segment set needs — the extra slots
        # stage as dead rows (all-zero live masks) and give incremental
        # refreshes free capacity to delta-append into without a
        # geometry rebuild
        self.slots_per_dev = max(1, -(-len(segments) // self.n_dev))
        if slots_per_dev is not None:
            self.slots_per_dev = max(self.slots_per_dev,
                                     int(slots_per_dev))
        self.n_slots = self.slots_per_dev * self.n_dev
        # set by release(): a query pinned to a replaced generation may
        # still lazily stage tables — those must NOT re-register under
        # the already-released ledger scope (see _account)
        self._released = False
        # serializes the lazy kernel/kNN cold stagings: two concurrent
        # first-queries must not both pay the transfer (and the loser's
        # re-registration would misclassify as a restage)
        self._kernel_stage_lock = threading.Lock()
        t0 = _time.monotonic()
        stacked = stack_shard_arrays(segments, self.n_slots)
        self.nd_pad = stacked.pop("nd_pad")
        self.nd1 = self.nd_pad + 1
        sharding = NamedSharding(self.mesh, PS("shards"))
        from elasticsearch_tpu.testing.disruption import on_device_staging

        # injection point for the base mesh staging (ISSUE 10): a raise
        # here aborts the constructor with nothing registered — the
        # owner's run_staged loop retries/classifies
        on_device_staging(self.index_name, "mesh_slot_tables",
                          "seg_stacked")
        self._seg_staged = {
            name: jax.device_put(arr, sharding)
            for name, arr in stacked.items()
        }
        self._sharding = sharding
        self._account("mesh_slot_tables", "seg_stacked",
                      sum(int(a.nbytes) for a in stacked.values()),
                      duration_ms=(_time.monotonic() - t0) * 1000.0)
        # per staged sort column: {"vocab": [terms]|None} — keyword sorts
        # rank by GLOBAL ordinals built over the staged segment set and
        # the caller maps ordinals back to terms for the response
        self.sort_meta: Dict[str, dict] = {}
        # lazily-staged tile-kernel plane (ensure_kernel): False =
        # unavailable, dict = {geom, meta: {id(seg): (bmin, bmax)}, mode}
        self._kernel = None
        # set when the HBM budget (not a fault) turned a staging away —
        # the ladder reports the demotion as decision reason hbm_budget.
        # Thread-local: each query reads the reason from ITS ensure_*
        # call, not a concurrent thread's reset
        self._denied = threading.local()
        # lazily-staged kNN plane per dense_vector field (ensure_knn):
        # field -> False | {emb, scale, mask, d_pad, nd_pad, metric}
        self._knn: Dict[str, object] = {}
        # per-(segment, geometry, lane posting-run) block-max bound
        # columns for pruning (invariant across queries — under zipfian
        # traffic the same hot terms recompute identical columns);
        # lifetime bounded by this executor (rebuilt on segment change)
        self._ub_cache: Dict[tuple, np.ndarray] = {}

    # ------------------------------------------------------------------
    # Device-memory accounting (ISSUE 9, docs/OBSERVABILITY.md)
    # ------------------------------------------------------------------

    @property
    def kernel_denied_reason(self):
        return getattr(self._denied, "reason", None)

    @kernel_denied_reason.setter
    def kernel_denied_reason(self, value) -> None:
        self._denied.reason = value

    def make_evictable(self, evict) -> None:
        """Arm the HBM-budget eviction callback — called by the owner
        AFTER this generation is installed as current. Arming during
        construction would let another thread's budget reservation evict
        this scope while the owner's executor pointer still names the
        PREVIOUS generation: the callback would drop and release the
        wrong one, and the owner's subsequent install would pin a staged
        key with no executor behind it (permanent host demotion)."""
        from elasticsearch_tpu.common.memory import memory_accountant

        self._evict_cb = evict
        memory_accountant().set_evict(self.index_name, self.scope, evict)

    def _account(self, kind: str, table: str, nbytes: int,
                 reason: Optional[str] = None, duration_ms: float = 0.0,
                 quiet: bool = False,
                 amplify_bytes: Optional[int] = None) -> None:
        from elasticsearch_tpu.common.memory import memory_accountant

        if self._released:
            # a query that pinned this generation before a concurrent
            # refresh replaced it may lazily stage MORE tables while
            # finishing: registering them would resurrect the released
            # scope (ledger bytes backed only by the query's transient
            # references, with an evict callback that would drop the
            # CURRENT generation). The arrays free with the query's
            # references; the ledger stays exact.
            return
        memory_accountant().register(
            self.index_name, self.scope, kind, table, int(nbytes),
            reason=reason or self._stage_reason, duration_ms=duration_ms,
            plane="mesh", evict=self._evict_cb, quiet=quiet,
            amplify_bytes=amplify_bytes)

    def release(self) -> int:
        """This executor generation is being replaced/dropped: return
        its staged bytes to the ledger. The arrays themselves free when
        the last in-flight query drops its references (refcounting)."""
        from elasticsearch_tpu.common.memory import memory_accountant

        self._released = True
        return memory_accountant().release_scope(self.index_name,
                                                 self.scope)

    def touch(self) -> None:
        from elasticsearch_tpu.common.memory import memory_accountant

        memory_accountant().touch(self.index_name, self.scope)

    # ------------------------------------------------------------------
    # Delta staging (ISSUE 20): incremental append + tombstone deletes
    # ------------------------------------------------------------------

    def free_slots(self) -> int:
        """Unoccupied slots in this generation (the append headroom)."""
        return self.n_slots - len(self.segments)

    @staticmethod
    def delta_append_compatible(old: "MeshPlanExecutor",
                                new_segments: List) -> bool:
        """Cheap structural pre-check: can ``new_segments`` delta-append
        into ``old``'s free slots without a geometry rebuild? False on
        any of the ISSUE 20 rebuild-fallback conditions (slots
        exhausted, tile-geometry mismatch); codec changes are the
        owner's check (it knows the live settings value)."""
        if old._released:
            return False
        if len(old.segments) + len(new_segments) > old.n_slots:
            return False  # slots exhausted
        bd = old._seg_staged.get("block_docs")
        nm = old._seg_staged.get("norms")
        if bd is None or nm is None:
            return False
        n_blocks, blk = int(bd.shape[1]), int(bd.shape[2])
        n_norm = int(nm.shape[1])
        kernel = old._kernel if isinstance(old._kernel, dict) else None
        n_rows = None
        if kernel is not None:
            from elasticsearch_tpu.ops import pallas_scoring as psc

            k_arr = old._seg_staged.get(
                "k_packed" if kernel["codec"] == "packed" else "k_docs")
            if k_arr is None:
                return False
            n_rows = int(k_arr.shape[1]) - psc.CB_MAX
        for seg in new_segments:
            if (seg.nd_pad > old.nd_pad
                    or seg.block_docs.shape[0] > n_blocks
                    or seg.block_docs.shape[1] != blk
                    or seg.norms.shape[0] > n_norm):
                return False  # tile-geometry mismatch
            if n_rows is not None and seg.block_docs.shape[0] > n_rows:
                return False  # kernel posting window would overflow
        return True

    @classmethod
    def delta_append(cls, old: "MeshPlanExecutor", append_pairs: List,
                     refresh_slots: List[int] = (),
                     index_name: Optional[str] = None
                     ) -> "MeshPlanExecutor":
        """Copy-on-write SUCCESSOR generation for an incremental refresh
        (ISSUE 20): stage ONLY the new segments' tables (postings, live
        masks, bound tables, embeddings) into free slots — every
        already-staged slot's arrays are shared with the old generation
        untouched (non-donating ``.at[slot].set`` scatters), so queries
        pinned to the old generation keep serving from intact arrays
        until the last reference drops.

        ``refresh_slots``: already-occupied slots whose live masks must
        also refresh (deletes riding along with the append).

        One transactional attempt inside the owner's run_staged loop:
        nothing publishes or registers until every array is built — a
        fault mid-way discards the half-built successor with the old
        generation and the ledger exactly as they were. The delta row
        bytes feed the amplification counters (reason ``delta_append``);
        the successor scope's full array bytes land in the ledger so
        release stays exact. Derived columns the append invalidates
        (sort keys — keyword global ordinals change with the vocab —
        slice masks, fused-agg doc values) are dropped and rebuild
        lazily. Raises ``_DeltaIneligible`` (a StagingBail: no retry, no
        fault accounting) on structural surprises the pre-check missed."""
        from elasticsearch_tpu.testing.disruption import on_device_staging

        new_segs = [seg for _sid, seg in append_pairs]
        if not cls.delta_append_compatible(old, new_segs):
            raise _DeltaIneligible("segment set cannot delta-append")
        self = cls.__new__(cls)
        self.mesh = old.mesh
        self.n_dev = old.n_dev
        self.index_name = index_name or old.index_name
        self.scope = f"mesh#{next(cls._SCOPE_SEQ)}"
        self.segments = list(old.segments) + new_segs
        self.pairs = list(old.pairs) + list(append_pairs)
        self._evict_cb = None
        # lazy stagings AFTER install classify as refresh (the segment
        # set did change); the construction below registers its delta
        # rows explicitly as delta_append
        self._stage_reason = "refresh"
        self.postings_codec_pref = old.postings_codec_pref
        self.postings_bytes_staged = old.postings_bytes_staged
        self.postings_codec = old.postings_codec
        self.slots_per_dev = old.slots_per_dev
        self.n_slots = old.n_slots
        self.nd_pad = old.nd_pad
        self.nd1 = old.nd1
        self._sharding = old._sharding
        self._released = False
        self._kernel_stage_lock = threading.Lock()
        self.sort_meta = {}
        self._kernel = None
        self._denied = threading.local()
        self._knn = {}
        self._ub_cache = {}
        self._seg_staged = {}

        t0 = _time.monotonic()
        base = old._seg_staged
        first_new = len(old.segments)
        new_slots = list(range(first_new, len(self.segments)))
        # live-mask rows refresh for appended slots AND tombstoned ones
        live_slots = sorted(set(refresh_slots)) + new_slots
        nd_pad = self.nd_pad

        # injection point (ISSUE 10 schemes): a raise here aborts the
        # attempt with nothing registered and the old generation intact
        on_device_staging(self.index_name, "mesh_slot_tables",
                          "delta_append")

        # --- base slot tables: delta rows at stacked geometry ---------
        n_blocks, blk = int(base["block_docs"].shape[1]), \
            int(base["block_docs"].shape[2])
        n_norm = int(base["norms"].shape[1])
        bd_rows = np.full((len(new_slots), n_blocks, blk), nd_pad,
                          np.int32)
        bt_rows = np.zeros((len(new_slots), n_blocks, blk), np.float32)
        nm_rows = np.ones((len(new_slots), n_norm, nd_pad + 1),
                          np.float32)
        for j, seg in enumerate(new_segs):
            bd = seg.block_docs.copy()
            bd[bd == seg.nd_pad] = nd_pad  # re-point sentinel
            bd_rows[j, : bd.shape[0]] = bd
            bt_rows[j, : seg.block_tfs.shape[0]] = seg.block_tfs
            nm_rows[j, : seg.norms.shape[0], : seg.norms.shape[1] - 1] \
                = seg.norms[:, :-1]
            nm_rows[j, :, nd_pad] = 1.0
        lv_rows = np.zeros((len(live_slots), nd_pad + 1), bool)
        for j, slot in enumerate(live_slots):
            seg = self.segments[slot]
            lv_rows[j, : seg.live.shape[0]] = seg.live
        idx_new = jnp.asarray(np.asarray(new_slots, np.int32))
        idx_live = jnp.asarray(np.asarray(live_slots, np.int32))
        staged = {
            "block_docs": jax.device_put(
                base["block_docs"].at[idx_new].set(jnp.asarray(bd_rows)),
                self._sharding),
            "block_tfs": jax.device_put(
                base["block_tfs"].at[idx_new].set(jnp.asarray(bt_rows)),
                self._sharding),
            "norms": jax.device_put(
                base["norms"].at[idx_new].set(jnp.asarray(nm_rows)),
                self._sharding),
            "live1": jax.device_put(
                base["live1"].at[idx_live].set(jnp.asarray(lv_rows)),
                self._sharding),
        }
        amp_base = int(bd_rows.nbytes + bt_rows.nbytes + nm_rows.nbytes
                       + lv_rows.nbytes)

        # --- kernel plane: delta posting windows + live_t rows --------
        kernel = old._kernel if isinstance(old._kernel, dict) else None
        live_t_amp: Dict[str, int] = {}
        amp_postings = 0
        amp_bounds = 0
        meta = None
        if kernel is not None:
            from elasticsearch_tpu.ops import pallas_scoring as psc

            geom, codec = kernel["geom"], kernel["codec"]
            k_key = "k_packed" if codec == "packed" else "k_docs"
            n_rows = int(base[k_key].shape[1])
            meta = dict(kernel["meta"])
            if codec == "packed":
                pk_rows = np.zeros((len(new_slots), n_rows, psc.LANE),
                                   np.int32)
            else:
                dc_rows = np.full((len(new_slots), n_rows, psc.LANE),
                                  nd_pad, np.int32)
                fr_rows = np.zeros((len(new_slots), n_rows, psc.LANE),
                                   np.float32)
            for j, seg in enumerate(new_segs):
                f = seg._block_frac()
                bmin, bmax = psc.block_min_max(
                    seg.block_docs, seg.block_tfs, seg.nd_pad)
                if codec == "packed":
                    fq = psc.quantize_frac(f)
                    pk = psc.pack_segment_blocks(seg.block_docs, f,
                                                 seg.nd_pad, q=fq)
                    if pk.shape[0] > n_rows:
                        raise _DeltaIneligible(
                            "packed posting window exceeds the staged "
                            "kernel rows")
                    pk_rows[j, : pk.shape[0]] = pk
                    bfmax = psc.block_frac_max(psc.dequantize_frac(fq))
                else:
                    dp, fp = psc.pad_segment_blocks(seg.block_docs, f,
                                                    seg.nd_pad)
                    if dp.shape[0] > n_rows:
                        raise _DeltaIneligible(
                            "raw posting window exceeds the staged "
                            "kernel rows")
                    dc_rows[j, : dp.shape[0]] = dp
                    fr_rows[j, : fp.shape[0]] = fp
                    bfmax = psc.block_frac_max(f)
                meta[id(seg)] = (bmin, bmax, bfmax)
                amp_bounds += sum(int(b.nbytes) for b in meta[id(seg)])
            if codec == "packed":
                staged["k_packed"] = jax.device_put(
                    base["k_packed"].at[idx_new].set(
                        jnp.asarray(pk_rows)), self._sharding)
                amp_postings = int(pk_rows.nbytes)
            else:
                staged["k_docs"] = jax.device_put(
                    base["k_docs"].at[idx_new].set(
                        jnp.asarray(dc_rows)), self._sharding)
                staged["k_frac"] = jax.device_put(
                    base["k_frac"].at[idx_new].set(
                        jnp.asarray(fr_rows)), self._sharding)
                amp_postings = int(dc_rows.nbytes + fr_rows.nbytes)
            for key in [k for k in base if k.startswith("k_live_t")]:
                g = (geom if key == "k_live_t" else psc.tile_geometry(
                    geom.nd_pad, int(key.rsplit("_", 1)[1])))
                lt_rows = np.zeros(
                    (len(live_slots), g.n_tiles * psc.LANE, g.tile_sub),
                    np.float32)
                for j, slot in enumerate(live_slots):
                    seg = self.segments[slot]
                    live = np.zeros(g.nd_pad, np.float32)
                    live[: seg.nd_pad] = seg.live.astype(np.float32)
                    lt_rows[j] = psc.build_live_t(live, g)
                staged[key] = jax.device_put(
                    base[key].at[idx_live].set(jnp.asarray(lt_rows)),
                    self._sharding)
                live_t_amp[key] = int(lt_rows.nbytes)

        # --- kNN planes: delta embedding/scale/mask rows per field ----
        knn_new: Dict[str, object] = {}
        knn_amp: Dict[str, Tuple[int, int, int]] = {}
        for field, entry in old._knn.items():
            if not isinstance(entry, dict):
                # None/False: the successor re-evaluates lazily (a new
                # segment may change the structural verdict either way)
                continue
            dims = entry.get("dims")
            if dims is None or any(
                    seg.vector_columns.get(field) is not None
                    and seg.vector_columns[field].dims != dims
                    for seg in new_segs):
                continue  # dims surprise: lazy restage decides
            import ml_dtypes

            from elasticsearch_tpu.ops import pallas_knn as pkn

            d_pad, nd_knn = entry["d_pad"], entry["nd_pad"]
            emb_rows = np.zeros((len(new_slots), nd_knn, d_pad),
                                ml_dtypes.bfloat16)
            sc_rows = np.zeros((len(new_slots), nd_knn, 1), np.float32)
            for j, seg in enumerate(new_segs):
                col = seg.vector_columns.get(field)
                if col is None:
                    continue  # slot stays dead
                emb_rows[j, : col.vectors.shape[0], : dims] = \
                    col.vectors.astype(ml_dtypes.bfloat16)
                sc = pkn.vector_scale_column(col.vectors,
                                             entry["metric"])
                sc_rows[j, : sc.shape[0]] = sc
            mk_rows = np.zeros((len(live_slots), nd_knn, 1), np.float32)
            for j, slot in enumerate(live_slots):
                seg = self.segments[slot]
                col = seg.vector_columns.get(field)
                if col is None:
                    continue
                m = (col.exists
                     & seg.live[: col.vectors.shape[0]]).astype(
                         np.float32)
                mk_rows[j, : m.shape[0], 0] = m
            knn_new[field] = dict(
                entry,
                emb=jax.device_put(
                    entry["emb"].at[idx_new].set(jnp.asarray(emb_rows)),
                    self._sharding),
                scale=jax.device_put(
                    entry["scale"].at[idx_new].set(
                        jnp.asarray(sc_rows)), self._sharding),
                mask=jax.device_put(
                    entry["mask"].at[idx_live].set(
                        jnp.asarray(mk_rows)), self._sharding))
            knn_amp[field] = (int(emb_rows.nbytes), int(sc_rows.nbytes),
                              int(mk_rows.nbytes))

        # --- commit: publish, then register (register-then-commit) ----
        self._seg_staged = staged
        self._knn = knn_new
        if kernel is not None:
            self._kernel = {"geom": kernel["geom"], "meta": meta,
                            "codec": kernel["codec"]}
        dur = (_time.monotonic() - t0) * 1000.0
        self._account(
            "mesh_slot_tables", "seg_stacked",
            sum(int(staged[k].nbytes) for k in
                ("block_docs", "block_tfs", "norms", "live1")),
            reason="delta_append", amplify_bytes=amp_base,
            duration_ms=dur)
        if kernel is not None:
            kind_postings = ("postings_packed"
                             if kernel["codec"] == "packed"
                             else "postings_raw")
            self._account(kind_postings, "k_postings",
                          self.postings_bytes_staged,
                          reason="delta_append",
                          amplify_bytes=amp_postings, duration_ms=dur)
            for key, amp in live_t_amp.items():
                self._account("live_mask", key,
                              int(staged[key].nbytes),
                              reason="delta_append", amplify_bytes=amp,
                              duration_ms=dur)
            self._account("bound_tables", "k_bounds",
                          sum(int(b.nbytes) for t in meta.values()
                              for b in t),
                          reason="delta_append",
                          amplify_bytes=amp_bounds)
        for field, entry in knn_new.items():
            e_amp, s_amp, m_amp = knn_amp[field]
            self._account("embeddings", f"knn:{field}",
                          int(entry["emb"].nbytes),
                          reason="delta_append", amplify_bytes=e_amp,
                          duration_ms=dur)
            self._account("scale_norm", f"knn_scale:{field}",
                          int(entry["scale"].nbytes),
                          reason="delta_append", amplify_bytes=s_amp,
                          duration_ms=dur)
            self._account("live_mask", f"knn_mask:{field}",
                          int(entry["mask"].nbytes),
                          reason="delta_append", amplify_bytes=m_amp,
                          duration_ms=dur)
        return self

    def apply_tombstones(self, slots: List[int]) -> int:
        """Tombstone deletes (ISSUE 20): recompute ONLY the given
        slots' live-mask columns — the base ``live1`` row (which also
        feeds the fused-agg matched masks), every staged kernel
        transposed-mask layout (``k_live_t`` + per-sub variants), and
        each staged kNN field's exists∧live mask — and publish them IN
        PLACE on this generation. No geometry rebuild, no scope change:
        the same ledger keys re-register at their (unchanged) full
        bytes with the changed ROW bytes as the amplification truth
        (reason ``tombstone``).

        One transactional attempt inside the owner's run_staged loop:
        every replacement array is built before anything publishes, so
        a fault leaves the generation serving the old masks and the
        ledger at its exact pre-attempt state. In-flight queries see
        either the old or the new masks — both are valid point-in-time
        views (the reference's flip-a-live-bit-under-readers contract).
        Returns the mask bytes actually restaged."""
        from elasticsearch_tpu.testing.disruption import on_device_staging

        with self._kernel_stage_lock:
            if self._released or not slots:
                return 0
            t0 = _time.monotonic()
            slots = sorted(slots)
            idx = jnp.asarray(np.asarray(slots, np.int32))
            # injection point (ISSUE 10): a raise here leaves nothing
            # published and nothing registered
            on_device_staging(self.index_name, "live_mask",
                              "tombstone_masks")
            nd_pad = self.nd_pad
            lv_rows = np.zeros((len(slots), nd_pad + 1), bool)
            for j, slot in enumerate(slots):
                seg = self.segments[slot]
                lv_rows[j, : seg.live.shape[0]] = seg.live
            updates = {"live1": jax.device_put(
                self._seg_staged["live1"].at[idx].set(
                    jnp.asarray(lv_rows)), self._sharding)}
            amp: Dict[str, int] = {"live1": int(lv_rows.nbytes)}
            if isinstance(self._kernel, dict):
                from elasticsearch_tpu.ops import pallas_scoring as psc

                geom = self._kernel["geom"]
                for key in [k for k in self._seg_staged
                            if k.startswith("k_live_t")]:
                    g = (geom if key == "k_live_t"
                         else psc.tile_geometry(
                             geom.nd_pad, int(key.rsplit("_", 1)[1])))
                    lt = np.zeros(
                        (len(slots), g.n_tiles * psc.LANE, g.tile_sub),
                        np.float32)
                    for j, slot in enumerate(slots):
                        seg = self.segments[slot]
                        live = np.zeros(g.nd_pad, np.float32)
                        live[: seg.nd_pad] = seg.live.astype(np.float32)
                        lt[j] = psc.build_live_t(live, g)
                    updates[key] = jax.device_put(
                        self._seg_staged[key].at[idx].set(
                            jnp.asarray(lt)), self._sharding)
                    amp[key] = int(lt.nbytes)
            knn_updates: Dict[str, dict] = {}
            knn_amp: Dict[str, int] = {}
            for field, entry in self._knn.items():
                if not isinstance(entry, dict):
                    continue
                nd_knn = entry["nd_pad"]
                mk = np.zeros((len(slots), nd_knn, 1), np.float32)
                for j, slot in enumerate(slots):
                    seg = self.segments[slot]
                    col = seg.vector_columns.get(field)
                    if col is None:
                        continue
                    m = (col.exists
                         & seg.live[: col.vectors.shape[0]]).astype(
                             np.float32)
                    mk[j, : m.shape[0], 0] = m
                knn_updates[field] = dict(entry, mask=jax.device_put(
                    entry["mask"].at[idx].set(jnp.asarray(mk)),
                    self._sharding))
                knn_amp[field] = int(mk.nbytes)
            restaged = sum(amp.values()) + sum(knn_amp.values())
            # commit: publish every replacement, then re-register the
            # same keys (full bytes unchanged; amplification = rows)
            self._seg_staged.update(updates)
            for field, entry in knn_updates.items():
                self._knn[field] = entry
            dur = (_time.monotonic() - t0) * 1000.0
            self._account(
                "mesh_slot_tables", "seg_stacked",
                sum(int(self._seg_staged[k].nbytes) for k in
                    ("block_docs", "block_tfs", "norms", "live1")),
                reason="tombstone", amplify_bytes=amp.pop("live1"),
                duration_ms=dur)
            for key, a in amp.items():
                self._account("live_mask", key,
                              int(self._seg_staged[key].nbytes),
                              reason="tombstone", amplify_bytes=a,
                              duration_ms=dur)
            for field, entry in knn_updates.items():
                self._account("live_mask", f"knn_mask:{field}",
                              int(entry["mask"].nbytes),
                              reason="tombstone",
                              amplify_bytes=knn_amp[field],
                              duration_ms=dur)
            return restaged

    # ------------------------------------------------------------------
    # Tile-kernel plane staging (the unified fast plane)
    # ------------------------------------------------------------------

    def ensure_kernel(self) -> Optional[dict]:
        """Stage the pallas tile-scoring plane over the stacked segment
        set: one SHARED tile geometry covering the stacked doc space, the
        per-segment posting windows (docs + per-posting BM25 norm factors,
        sentinel-padded so every CB-aligned DMA window is in bounds)
        packed per slot, and the per-slot transposed live masks. Returns
        the kernel session (plan builders consult it via
        ``ctx.mesh_kernel``) or None when the kernel can't run (pallas
        off / non-TPU backend without interpret mode).

        Staging is TRANSACTIONAL (ISSUE 10, docs/RESILIENCE.md): a fault
        mid-sequence drops every partially-published ``_seg_staged``
        entry (nothing registers with the accountant until the whole
        group staged — no orphaned HBM bytes); transient device faults
        retry with bounded backoff (``search.staging.retry.*``), and a
        terminal fault sets ``kernel_denied_reason = "staging_fault"``
        (the caller quarantines the plane) while ``_kernel`` stays None
        so the post-cooldown probe can restage once the fault clears."""
        from elasticsearch_tpu.ops.aggs import _pallas_mode

        # reset FIRST — before every early return: a thread whose last
        # call was a budget denial must not keep reporting hbm_budget
        # for what is now a mode gap or staging fault (the reason is
        # thread-local, so only its own reset clears it)
        self.kernel_denied_reason = None
        mode = _pallas_mode()
        if not mode:
            return None
        from elasticsearch_tpu.common.memory import memory_accountant
        from elasticsearch_tpu.common.staging import run_staged
        from elasticsearch_tpu.ops import pallas_scoring as psc

        if self._kernel is None:
            with self._kernel_stage_lock:
                if isinstance(self._kernel, dict):  # a racing cold
                    return dict(self._kernel, mode=mode)  # stager built it
                geom = psc.tile_geometry(max(self.nd_pad, psc.LANE))
                # codec resolution against the STACKED doc space: every
                # slot's doc ids must fit the packed word's doc bits
                codec = psc.resolve_postings_codec(
                    self.postings_codec_pref, geom.nd_pad)
                n_rows = max(s.block_docs.shape[0]
                             for s in self.segments) + psc.CB_MAX
                # HBM budget gate: the kernel tables are the big mesh
                # allocation — over budget (after LRU eviction) the
                # ladder serves from the scatter mesh / host rung with
                # decision reason hbm_budget; _kernel stays None so a
                # freed budget lets a later query stage them
                # packed: one i32 word/posting; raw: i32 docs + f32 frac
                word = 4 if codec == "packed" else 8
                estimate = (self.n_slots * n_rows * psc.LANE * word
                            + self.n_slots * geom.n_tiles * psc.LANE
                            * geom.tile_sub * 4)
                if not memory_accountant().try_reserve(
                        self.index_name, estimate,
                        exclude_scope=self.scope):
                    self.kernel_denied_reason = "hbm_budget"
                    return None
                try:
                    run_staged(
                        lambda: self._stage_kernel_plane(geom, codec,
                                                         n_rows),
                        index=self.index_name, kind="postings_" + (
                            "packed" if codec == "packed" else "raw"),
                        plane="mesh")  # retry: process-level config
                except Exception:  # noqa: BLE001 — classified terminal
                    # staging fault (rollback already ran): the caller
                    # demotes + quarantines; retryable on the probe
                    _plane_logger.warning(
                        "[%s] mesh kernel staging failed; plane demotes "
                        "with reason staging_fault", self.index_name,
                        exc_info=True)
                    self.kernel_denied_reason = "staging_fault"
                    return None
        return dict(self._kernel, mode=mode)

    def _stage_kernel_plane(self, geom, codec: str, n_rows: int) -> None:
        """One staging ATTEMPT of the kernel plane (runs inside
        run_staged's retry loop — the injection hooks below re-consult
        the schemes on every retry). Publishes ``_seg_staged`` entries
        and ledger registrations only on full success; any fault rolls
        both back before re-raising."""
        from elasticsearch_tpu.ops import pallas_scoring as psc
        from elasticsearch_tpu.testing.disruption import on_device_staging

        t0 = _time.monotonic()
        kind_postings = ("postings_packed" if codec == "packed"
                         else "postings_raw")
        try:
            if codec == "packed":
                packed = np.zeros((self.n_slots, n_rows, psc.LANE),
                                  np.int32)
            else:
                docs = np.full((self.n_slots, n_rows, psc.LANE),
                               self.nd_pad, np.int32)
                frac = np.zeros((self.n_slots, n_rows, psc.LANE),
                                np.float32)
            live_t = np.zeros(
                (self.n_slots, geom.n_tiles * psc.LANE, geom.tile_sub),
                np.float32)
            meta = {}
            for i, seg in enumerate(self.segments):
                f = seg._block_frac()
                bmin, bmax = psc.block_min_max(
                    seg.block_docs, seg.block_tfs, seg.nd_pad)
                if codec == "packed":
                    fq = psc.quantize_frac(f)  # one pass serves both
                    pk = psc.pack_segment_blocks(seg.block_docs, f,
                                                 seg.nd_pad, q=fq)
                    packed[i, : pk.shape[0]] = pk
                    # bounds must dominate the DEQUANTIZED values the
                    # kernel decodes (rounding can lift a posting up
                    # to half a quantization step)
                    bfmax = psc.block_frac_max(psc.dequantize_frac(fq))
                else:
                    dp, fp = psc.pad_segment_blocks(seg.block_docs, f,
                                                    seg.nd_pad)
                    docs[i, : dp.shape[0]] = dp
                    frac[i, : fp.shape[0]] = fp
                    bfmax = psc.block_frac_max(f)
                live = np.zeros(geom.nd_pad, np.float32)
                live[: seg.nd_pad] = seg.live.astype(np.float32)
                live_t[i] = psc.build_live_t(live, geom)
                meta[id(seg)] = (bmin, bmax, bfmax)
            on_device_staging(self.index_name, kind_postings, "k_postings")
            if codec == "packed":
                self._seg_staged["k_packed"] = jax.device_put(
                    packed, self._sharding)
                self.postings_bytes_staged = int(packed.nbytes)
            else:
                self._seg_staged["k_docs"] = jax.device_put(
                    docs, self._sharding)
                self._seg_staged["k_frac"] = jax.device_put(
                    frac, self._sharding)
                self.postings_bytes_staged = int(docs.nbytes + frac.nbytes)
            on_device_staging(self.index_name, "live_mask", "k_live_t")
            self._seg_staged["k_live_t"] = jax.device_put(
                live_t, self._sharding)
        except BaseException:
            # transactional rollback: no partially-published table may
            # survive the attempt (a half-staged plane would serve a
            # later query with missing arrays) and nothing was
            # registered with the accountant yet — no orphaned bytes
            for key in ("k_packed", "k_docs", "k_frac", "k_live_t"):
                self._seg_staged.pop(key, None)
            self.postings_bytes_staged = 0
            raise
        # commit: publish the session, THEN register the exact bytes
        # (register-then-commit — the ledger never holds bytes for a
        # generation that failed to install)
        self.postings_codec = codec
        self._kernel = {"geom": geom, "meta": meta, "codec": codec}
        dur = (_time.monotonic() - t0) * 1000.0
        self._account(kind_postings, "k_postings",
                      self.postings_bytes_staged, duration_ms=dur)
        self._account("live_mask", "k_live_t", int(live_t.nbytes),
                      duration_ms=dur)
        # per-segment block min/max/frac-max bound columns stay
        # host-resident but scale with the staged plane
        self._account("bound_tables", "k_bounds", sum(
            int(b.nbytes) for t in meta.values() for b in t))

    def ensure_knn(self, field: str, dims: int,
                   metric: str) -> Optional[dict]:
        """Stage a dense_vector field's kNN plane over the stacked
        segment set: per-slot bf16 embedding matrices [n_slots, nd_pad,
        d_pad], the metric scale columns (cosine inverse norms / ones)
        and the live∧has-vector mask columns — packed on the SAME
        collective geometry as the postings staging, so the kNN program
        reuses the executor's mesh/sharding/slot mapping verbatim.
        Deletes are honored through the mask: IndexMeshSearch rebuilds
        the executor (and with it this staging) whenever any segment's
        live_doc_count changes. Returns the session dict or None when
        the kernel can't run here."""
        from elasticsearch_tpu.ops.aggs import _pallas_mode

        # reset FIRST — before every early return (same contract as
        # ensure_kernel: a stale thread-local hbm_budget must not
        # relabel a mode gap or staging fault)
        self.kernel_denied_reason = None
        mode = _pallas_mode()
        if not mode:
            return None
        entry = self._knn.get(field)
        if entry is False:
            return None
        if entry is None:
            from elasticsearch_tpu.common.staging import run_staged

            with self._kernel_stage_lock:
                entry = self._knn.get(field)
                if isinstance(entry, dict):  # racing cold stager built it
                    return dict(entry, mode=mode)
                if entry is False:
                    return None
                try:
                    entry = run_staged(
                        lambda: self._stage_knn_plane(field, dims, metric),
                        index=self.index_name, kind="embeddings",
                        plane="mesh")  # retry: process-level config
                except _KnnStructuralError:
                    # a REQUEST/mapping-shaped inability (dims mismatch
                    # across segments): permanent for this segment set,
                    # never a device fault — plane stays host quietly
                    self._knn[field] = False
                    return None
                except Exception:  # noqa: BLE001 — classified terminal
                    # staging fault (rollback ran): demote + quarantine;
                    # the entry stays None so the probe restages
                    _plane_logger.warning(
                        "[%s] mesh kNN staging failed for [%s]; plane "
                        "demotes with reason staging_fault",
                        self.index_name, field, exc_info=True)
                    self.kernel_denied_reason = "staging_fault"
                    return None
                if entry is None:  # hbm_budget denial inside the attempt
                    return None
        return dict(entry, mode=mode)

    def _stage_knn_plane(self, field: str, dims: int,
                         metric: str) -> Optional[dict]:
        """One staging ATTEMPT of a dense_vector field's kNN plane
        (inside run_staged's retry loop). Returns the session entry, or
        None on an HBM-budget denial; register-then-commit like
        _stage_kernel_plane."""
        import ml_dtypes

        from elasticsearch_tpu.common.memory import memory_accountant
        from elasticsearch_tpu.ops import pallas_knn as pkn
        from elasticsearch_tpu.ops import pallas_scoring as psc
        from elasticsearch_tpu.testing.disruption import on_device_staging

        t0 = _time.monotonic()
        d_pad = pkn.pad_dims(dims)
        nd_knn = max(self.nd_pad, psc.LANE)
        # HBM budget gate (same demotion contract as ensure_kernel):
        # over budget the kNN batch serves from the host plan-node
        # rung, reason hbm_budget
        estimate = self.n_slots * nd_knn * (d_pad * 2 + 8)
        if not memory_accountant().try_reserve(
                self.index_name, estimate, exclude_scope=self.scope):
            self.kernel_denied_reason = "hbm_budget"
            return None
        emb = np.zeros((self.n_slots, nd_knn, d_pad), ml_dtypes.bfloat16)
        scale = np.zeros((self.n_slots, nd_knn, 1), np.float32)
        mask = np.zeros((self.n_slots, nd_knn, 1), np.float32)
        for i, seg in enumerate(self.segments):
            col = seg.vector_columns.get(field)
            if col is None:
                continue  # slot stays dead (mask all-zero)
            if col.dims != dims:
                raise _KnnStructuralError(
                    f"segment [{seg.name}] stores [{field}] at "
                    f"dims={col.dims}, mapping says {dims}")
            # the host mirror is already on the bf16 grid: the
            # astype below is exact
            emb[i, : col.vectors.shape[0], : dims] = \
                col.vectors.astype(ml_dtypes.bfloat16)
            sc = pkn.vector_scale_column(col.vectors, metric)
            live = seg.live[: col.vectors.shape[0]]
            m = (col.exists & live).astype(np.float32)
            scale[i, : sc.shape[0]] = sc
            mask[i, : m.shape[0], 0] = m
        on_device_staging(self.index_name, "embeddings", f"knn:{field}")
        # all three device transfers must land before anything
        # publishes: a fault between them leaves only unreferenced
        # arrays for the GC (nothing in _seg_staged / the ledger)
        entry = {
            "emb": jax.device_put(emb, self._sharding),
            "scale": jax.device_put(scale, self._sharding),
            "mask": jax.device_put(mask, self._sharding),
            "d_pad": d_pad,
            "nd_pad": nd_knn,
            "metric": metric,
            # mapping dims: delta_append verifies a new segment's column
            # against it before carrying this plane forward (ISSUE 20)
            "dims": dims,
        }
        self._knn[field] = entry
        dur = (_time.monotonic() - t0) * 1000.0
        self._account("embeddings", f"knn:{field}",
                      int(emb.nbytes), duration_ms=dur)
        self._account("scale_norm", f"knn_scale:{field}",
                      int(scale.nbytes), duration_ms=dur)
        self._account("live_mask", f"knn_mask:{field}",
                      int(mask.nbytes), duration_ms=dur)
        return entry

    def tile_lane_ub_cached(self, seg, union_lanes, row_lo, row_hi,
                            bfmax, sub: int) -> np.ndarray:
        """Per-(tile, lane) block-max bounds with per-lane caching: a
        lane's column depends only on (segment, tile geometry, posting
        run) — row windows come deterministically from the run's
        per-block doc ranges — so repeat queries on hot terms reuse it
        instead of re-gathering on the query hot path."""
        from elasticsearch_tpu.ops import pallas_scoring as psc

        n_tiles, t_pad = row_lo.shape
        ub = np.zeros((n_tiles, t_pad), np.float32)
        grew = False
        for j, lane in enumerate(union_lanes):
            key = (id(seg), sub, lane.block_start, lane.block_count)
            col = self._ub_cache.get(key)
            if col is None or col.shape[0] != n_tiles:
                if len(self._ub_cache) > 4096:  # runaway-vocab backstop
                    self._ub_cache.clear()
                col = psc.tile_lane_ub(row_lo[:, j: j + 1],
                                       row_hi[:, j: j + 1], bfmax)[:, 0]
                self._ub_cache[key] = col
                grew = True
            ub[:, j] = col
        if grew:
            # accumulator-style ledger entry: re-register the cache's
            # CURRENT total (quiet — per-lane growth is not a staging
            # lifecycle event, docs/OBSERVABILITY.md)
            self._account("bound_tables", "ub_cache",
                          sum(int(c.nbytes)
                              for c in self._ub_cache.values()),
                          quiet=True)
        return ub

    def ensure_kernel_live(self, sub: int) -> str:
        """Per-sub live-mask layout for a shrunk tile geometry (dense-term
        queries — the geometry ladder); mirrors Segment.kernel_live_t_for
        but over the stacked slot axis."""
        from elasticsearch_tpu.ops import pallas_scoring as psc

        key = f"k_live_t_{sub}"
        if key not in self._seg_staged:
            from elasticsearch_tpu.testing.disruption import (
                on_device_staging,
            )

            t0 = _time.monotonic()
            geom = psc.tile_geometry(self._kernel["geom"].nd_pad, sub)
            live_t = np.zeros(
                (self.n_slots, geom.n_tiles * psc.LANE, geom.tile_sub),
                np.float32)
            for i, seg in enumerate(self.segments):
                live = np.zeros(geom.nd_pad, np.float32)
                live[: seg.nd_pad] = seg.live.astype(np.float32)
                live_t[i] = psc.build_live_t(live, geom)
            # a raise here lands in the calling launch's fault handler
            # (per-sub mask variants stage inside the launch try)
            on_device_staging(self.index_name, "live_mask", key)
            self._seg_staged[key] = jax.device_put(live_t, self._sharding)
            self._account("live_mask", key, int(live_t.nbytes),
                          reason="geometry_change",
                          duration_ms=(_time.monotonic() - t0) * 1000.0)
        return key

    def harmonize_kernel_nodes(self, plans: List[PlanNode]) -> int:
        """Finalize every deferred mesh kernel node so table shapes agree
        across the whole segment set: one (tile_sub, t_pad, cb) for each
        aligned node group, chosen by the geometry ladder collectively
        (a dense term on ANY shard shrinks everyone's tile). Returns the
        number of kernel node groups finalized; raises
        PlanStructureMismatch when no shared geometry exists (caller
        retries with scatter nodes)."""
        from elasticsearch_tpu.index.segment import next_pow2
        from elasticsearch_tpu.ops import pallas_scoring as psc
        from elasticsearch_tpu.search.plan import PallasScoreTermsNode

        groups: List[List[PlanNode]] = []

        def walk(nodes):
            if all(isinstance(n, PallasScoreTermsNode) for n in nodes):
                groups.append(list(nodes))
            kids = [n.children() for n in nodes]
            if len({len(ks) for ks in kids}) != 1:
                raise PlanStructureMismatch("tree arity diverges")
            for child_set in zip(*kids):
                walk(list(child_set))

        walk(plans)
        if not groups:
            return 0
        session = self._kernel
        if not isinstance(session, dict):
            raise PlanStructureMismatch("kernel plane not staged")
        geom = session["geom"]
        tps = psc.tiles_per_step_default()
        for nodes in groups:
            if any(n._mesh_lanes is None for n in nodes):
                raise PlanStructureMismatch(
                    "kernel/scatter node mix across shards")
            t_pad = max(next_pow2(max(len(n._mesh_lanes), 1))
                        for n in nodes)
            sub = geom.tile_sub
            while True:
                g = geom if sub == geom.tile_sub else psc.tile_geometry(
                    geom.nd_pad, sub)
                try:
                    tables = [psc.build_tile_tables(
                        n._mesh_lanes, n._mesh_bmin, n._mesh_bmax, g,
                        t_pad=t_pad) for n in nodes]
                    break
                except ValueError:
                    # covering window exceeded the kernel bound somewhere
                    # (or malformed ranges at the ladder floor)
                    if sub <= 32 or g.tile_sub < sub:
                        raise PlanStructureMismatch(
                            "no shared kernel geometry for this query")
                    sub //= 2
            cb = max(t[3] for t in tables)
            live_key = ("k_live_t" if g.tile_sub == geom.tile_sub
                        else self.ensure_kernel_live(g.tile_sub))
            for n, (rl, rh, w, _cb) in zip(nodes, tables):
                n.finalize_mesh(rl, rh, w, cb=cb, sub=g.tile_sub,
                                live_key=live_key, tiles_per_step=tps)
        return len(groups)

    def ensure_sort_column(self, field: str, order: str, missing) -> Optional[
            Tuple[str, str]]:
        """Stage (oriented key, raw values) columns for a single-field sort
        and return their seg-dict names, or None if the field can't sort
        exactly on the mesh.

        The in-program rank key is f32; a float64 column only qualifies if
        every value is exactly f32-representable (timestamps usually are
        not — resolution 2^-24 relative — and silently reordering near-tied
        dates would be wrong, so those fall back to the host path). The
        oriented key follows _sort_keys: negate for asc, missing-fill with
        finite sentinels so -inf stays reserved for "not matched".

        Keyword fields rank by GLOBAL ordinals: per-segment ordinal spaces
        are meaningless across shards (the reference's global-ordinals
        problem, fielddata/ordinals/GlobalOrdinalsBuilder), so the staged
        key is each doc's position in the sorted union of every staged
        segment's terms — exact in f32 for < 2^24 distinct terms."""
        token = (repr(missing) if isinstance(missing, (int, float))
                 else str(missing or "_last"))
        name = f"msort.{field}.{order}.{token}"
        if name in self._seg_staged:
            return name, name + ".raw"
        ords = [s.ordinal_columns.get(field)
                or s.ordinal_columns.get(f"{field}.keyword")
                for s in self.segments]
        if any(o is not None for o in ords):
            return self._ensure_keyword_sort_column(
                name, ords, order, missing)
        big = np.float32(3.0e38)
        keys = np.zeros((self.n_slots, self.nd1), np.float32)
        raws = np.zeros((self.n_slots, self.nd1), np.float32)
        for i, seg in enumerate(self.segments):
            if field == "_doc":
                if seg.nd_pad > (1 << 24):
                    return None  # doc id not f32-exact
                raw = np.arange(seg.nd_pad, dtype=np.float64)
                exists = np.ones(seg.nd_pad, bool)
            else:
                col = seg.numeric_columns.get(field)
                if col is None:
                    return None
                raw = (col.min_value if order == "asc"
                       else col.max_value).astype(np.float64)
                exists = col.exists
                vals = raw[exists]
                if not np.array_equal(
                        vals, vals.astype(np.float32).astype(np.float64)):
                    return None  # not exactly f32-representable
            if missing is None or missing == "_last":
                fill = np.float64(-big if order == "desc" else big)
            elif missing == "_first":
                fill = np.float64(big if order == "desc" else -big)
            else:
                fill = np.float64(missing)
            raw = np.where(exists, raw, fill)
            key = np.clip(raw if order == "desc" else -raw, -big, big)
            keys[i, : seg.nd_pad] = key.astype(np.float32)
            keys[i, seg.nd_pad:] = -big  # padding never outranks real docs
            raws[i, : seg.nd_pad] = raw.astype(np.float32)
        self._seg_staged[name] = jax.device_put(keys, self._sharding)
        self._seg_staged[name + ".raw"] = jax.device_put(
            raws, self._sharding)
        # sort key columns are doc-values-plane tables (ISSUE 13): they
        # derive from the same sealed columns the fused aggs stage, so
        # they account under the doc_values ledger kind (docs/AGGS.md)
        self._account("doc_values", name,
                      int(keys.nbytes + raws.nbytes))
        self.sort_meta[name] = {"vocab": None}
        return name, name + ".raw"

    def _ensure_keyword_sort_column(self, name: str, ords: List,
                                    order: str, missing) -> Optional[
            Tuple[str, str]]:
        """Global-ordinal key columns for a keyword sort (see
        ensure_sort_column). `ords`: per-segment ordinal column or None
        (None = every doc in that segment is missing)."""
        if missing not in (None, "_last", "_first"):
            return None  # custom-string missing ranks mid-vocab: host path
        vocab: List[str] = sorted(
            set().union(*(o.terms for o in ords if o is not None)))
        if len(vocab) >= (1 << 24):
            return None  # ordinal not f32-exact
        big = np.float32(3.0e38)
        if missing == "_first":
            fill = np.float64(big if order == "desc" else -big)
        else:
            fill = np.float64(-big if order == "desc" else big)
        keys = np.zeros((self.n_slots, self.nd1), np.float32)
        raws = np.zeros((self.n_slots, self.nd1), np.float32)
        for i, (seg, ocol) in enumerate(zip(self.segments, ords)):
            if ocol is None:
                raw = np.full(seg.nd_pad, fill)
            else:
                # local ordinal -> global ordinal (terms are sorted, so
                # searchsorted is the OrdinalMap build)
                g = np.searchsorted(vocab, ocol.terms).astype(np.float64)
                raw = np.where(ocol.exists, g[ocol.first_ord], fill)
            key = np.clip(raw if order == "desc" else -raw, -big, big)
            keys[i, : seg.nd_pad] = key.astype(np.float32)
            keys[i, seg.nd_pad:] = -big
            raws[i, : seg.nd_pad] = raw.astype(np.float32)
        self._seg_staged[name] = jax.device_put(keys, self._sharding)
        self._seg_staged[name + ".raw"] = jax.device_put(
            raws, self._sharding)
        self._account("doc_values", name,
                      int(keys.nbytes + raws.nbytes))
        self.sort_meta[name] = {"vocab": vocab}
        return name, name + ".raw"

    def ensure_slice_column(self, slice_spec: dict,
                            shard_of_device: List[int],
                            num_shards: int) -> Optional[str]:
        """Stage the deterministic slice doc partition as a boolean mask
        column, shard-aware like the host path (SliceBuilder.toFilter's
        three regimes — see search/service.resolve_slice); shares the
        host path's per-segment mask cache."""
        from elasticsearch_tpu.search.service import resolve_slice
        from elasticsearch_tpu.utils.murmur3 import hash_slice_id

        sid = int(slice_spec["id"])
        smax = int(slice_spec["max"])
        name = f"mslice.{smax}.{sid}.{num_shards}"
        if name in self._seg_staged:
            return name
        out = np.zeros((self.n_slots, self.nd1), bool)
        for i, seg in enumerate(self.segments):
            resolved = resolve_slice(slice_spec, shard_of_device[i],
                                     num_shards)
            if resolved == "skip":
                continue  # all-False row
            if resolved is None:
                out[i, : seg.nd_pad] = True  # whole shard in the slice
                continue
            rid, rmax = int(resolved["id"]), int(resolved["max"])
            cache_key = f"slice.{rmax}.{rid}"  # same key the host uses
            mask = seg.dev_cache.get(cache_key)
            if mask is None:
                mask = np.zeros(seg.nd_pad + 1, dtype=bool)
                for local, doc_id in enumerate(seg.doc_ids):
                    if hash_slice_id(doc_id) % rmax == rid:
                        mask[local] = True
                seg.dev_cache[cache_key] = mask
            out[i, : mask.shape[0]] = mask
        self._seg_staged[name] = jax.device_put(out, self._sharding)
        self._account("mesh_slot_tables", name, int(out.nbytes))
        return name

    def stage_doc_value_columns(self, builds: Dict[str, object]) -> bool:
        """Stage fused-aggregation doc-value columns (ISSUE 13,
        docs/AGGS.md): ``builds`` maps a representative table name to a
        callable producing ``{name: np.ndarray}`` groups of per-slot
        columns. Registered under the ``doc_values`` ledger kind with
        the PR-9/PR-10 contracts: budget-gated (``try_reserve`` — a
        denial returns False and the caller demotes the aggs to the
        host reduce with reason ``hbm_budget``), TRANSACTIONAL
        (register-then-commit: nothing publishes or registers until
        every transfer landed; a fault mid-group leaves no trace), and
        evictable with this executor generation's scope. Transient
        device faults retry with the classified backoff
        (``search.staging.retry.*``); a terminal fault propagates to
        the caller (fallback reason ``staging_fault``)."""
        from elasticsearch_tpu.common.memory import memory_accountant
        from elasticsearch_tpu.common.staging import run_staged

        with self._kernel_stage_lock:
            arrays: Dict[str, np.ndarray] = {}
            for fn in builds.values():
                for name, arr in fn().items():
                    if name not in self._seg_staged:
                        arrays[name] = arr
            if not arrays:
                return True
            estimate = sum(int(a.nbytes) for a in arrays.values())
            if not memory_accountant().try_reserve(
                    self.index_name, estimate, exclude_scope=self.scope):
                return False

            def _attempt():
                from elasticsearch_tpu.testing.disruption import (
                    on_device_staging,
                )

                t0 = _time.monotonic()
                on_device_staging(self.index_name, "doc_values",
                                  "agg_columns")
                staged = {name: jax.device_put(a, self._sharding)
                          for name, a in arrays.items()}
                # publish atomically-enough (dict.update under the GIL)
                # AFTER every transfer landed, then register the exact
                # bytes — a fault above leaves nothing behind
                self._seg_staged.update(staged)
                dur = (_time.monotonic() - t0) * 1000.0
                for name, a in arrays.items():
                    self._account("doc_values", name, int(a.nbytes),
                                  duration_ms=dur)

            run_staged(_attempt, index=self.index_name,
                       kind="doc_values", plane="mesh")
        return True

    def execute(self, plans: List[PlanNode], k: int,
                sort_keys: Optional[Tuple[str, str]] = None,
                with_views: bool = False,
                pf_plans: Optional[List[PlanNode]] = None,
                rs_plans: Optional[List[PlanNode]] = None,
                scalars: Optional[dict] = None,
                features: frozenset = frozenset(),
                slice_col: Optional[str] = None,
                rescore_static: Optional[Tuple[int, str]] = None,
                tracer=None, agg_static: tuple = ()):
        """plans: one per shard, same query. Returns (top_keys [k],
        top_shard [k], top_doc [k], total, top_score [k], top_raw [k]
        [, matched [n_dev, nd1], scores [n_dev, nd1]]
        [, fused-agg partials...]) — doc ids are in the STACKED doc
        space (valid per-shard ids since every shard zero-bases).

        pf_plans / rs_plans: optional per-shard post_filter and rescore
        query plans; scalars: traced values for `features` and rescore
        weights (compiled once per feature SET, not per value).
        agg_static: fused-agg descriptors (search/fused_aggs.py) whose
        staged doc-value columns reduce inside the program."""
        if len(plans) != len(self.segments):
            raise ValueError("one plan per staged shard required")
        if tracer is None:
            from elasticsearch_tpu.search.telemetry import NULL_TRACER

            tracer = NULL_TRACER
        t_stage = tracer.start("staging")
        local_pads = [s.nd_pad for s in self.segments]
        stacked = stack_plans(plans, local_pads, self.nd1, self.n_slots)
        key_parts = [plans[0].key(), _shapes_sig(stacked)]
        stacked_pf: List[np.ndarray] = []
        stacked_rs: List[np.ndarray] = []
        pf_tpl = rs_tpl = None
        if pf_plans:
            stacked_pf = stack_plans(pf_plans, local_pads, self.nd1,
                                     self.n_slots)
            pf_tpl = _strip_plan(pf_plans[0])
            key_parts += ["pf:" + pf_plans[0].key(), _shapes_sig(stacked_pf)]
        if rs_plans:
            stacked_rs = stack_plans(rs_plans, local_pads, self.nd1,
                                     self.n_slots)
            rs_tpl = _strip_plan(rs_plans[0])
            key_parts += ["rs:" + rs_plans[0].key(), _shapes_sig(stacked_rs)]
        key = ("|".join(key_parts)
               + f"|k{k}|n{self.n_dev}|p{self.slots_per_dev}"
               + f"|s{sort_keys}|v{with_views}"
               + f"|f{sorted(features)}|sl{slice_col}|r{rescore_static}"
               + f"|a{agg_static}")
        run = _mesh_query_program(
            self.mesh,
            _TemplateHolder(_strip_plan(plans[0]), key, pf_tpl, rs_tpl), k,
            spd=self.slots_per_dev,
            sort_keys=sort_keys, with_views=with_views, features=features,
            slice_col=slice_col, rescore_static=rescore_static,
            agg_static=agg_static)
        staged_plan = [jax.device_put(a, self._sharding) for a in stacked]
        staged_pf = [jax.device_put(a, self._sharding) for a in stacked_pf]
        staged_rs = [jax.device_put(a, self._sharding) for a in stacked_rs]
        jscalars = {name: jnp.float32(v)
                    for name, v in (scalars or {}).items()}
        tracer.stop("staging", t_stage)
        t_kernel = tracer.start("kernel")
        with _MESH_EXEC_LOCK:
            outs = run(self._seg_staged, staged_plan, staged_pf, staged_rs,
                       jscalars)
            # dispatch is async: the collectives execute after run()
            # returns, so completion must happen INSIDE the lock (the
            # caller fetches the results immediately anyway)
            jax.block_until_ready(outs)
        tracer.stop("kernel", t_kernel)
        return outs
