"""The production mesh data plane: ANY query plan over a device mesh.

Round-1's `parallel/distributed.py` proved the collectives pattern on one
hardcoded disjunction kernel; this module generalizes it to the full query
DSL. The per-shard plans built by ``QueryBuilder.to_plan`` (identical tree
structure, shard-local arrays) are STACKED — every plan array padded to a
common shape with a leading ``[n_devices]`` axis — and the template plan's
``emit`` is traced ONCE inside ``shard_map``. The result is one compiled
XLA program executing the whole scatter-gather:

  per-device:  plan.emit -> (scores, matched) over the local shard
               -> local lax.top_k
  collective:  all_gather(top-k) over ICI -> global top-k on every device
               (the TopDocs.merge analog,
               action/search/SearchPhaseController.java:408)
               psum(total_hits) (+ psum'd agg partials, aggs_mesh.py)

Per-array padding semantics come from ``PlanNode.pad_kinds`` — padded
lanes either carry ``valid=False`` masks or scatter onto the stacked
sentinel doc (``nd1-1``), which ``live1`` kills.

Reference: the RPC fan-out this replaces is
action/search/AbstractSearchAsyncAction.java + SearchTransportService
("indices:data/read/search[phase/query]"), per SURVEY.md §5.7/§5.8.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from elasticsearch_tpu.search.plan import EmitCtx, PlanNode


class PlanStructureMismatch(Exception):
    """Per-shard plans for the same query diverged structurally (e.g. a
    field exists on one shard only with a different similarity) — the
    caller falls back to the host-merge path."""


def _check_same_structure(plans: List[PlanNode]) -> None:
    def skeleton(p: PlanNode):
        # trace_statics participates: a static parameter baked into the
        # template's trace (similarity kinds, range relation, boost_mode)
        # that diverges per shard would silently score non-template
        # shards with the wrong formula
        return (type(p).__name__, len(p.arrays()), p.trace_statics(),
                tuple(skeleton(c) for c in p.children()))

    first = skeleton(plans[0])
    for p in plans[1:]:
        if skeleton(p) != first:
            raise PlanStructureMismatch(
                f"{skeleton(p)} != {first}")


_PAD_VALUES = {"z": 0, "o": 1, "n": np.nan, "m1": -1}


def stack_plans(plans: List[PlanNode], local_nd_pads: List[int],
                stacked_nd1: int, n_devices: int) -> List[np.ndarray]:
    """Stack per-shard plan arrays to mesh-ready arrays.

    Returns a flat list aligned with ``template.flat_arrays()`` where every
    entry has a leading [n_devices] axis. Device slots beyond len(plans)
    replicate shard 0's arrays — their seg arrays have live1 all-False, so
    they contribute nothing.
    """
    _check_same_structure(plans)
    kinds = plans[0].flat_pad_kinds()
    flats = [[np.asarray(a) for a in p.flat_arrays()] for p in plans]
    n_arrays = len(kinds)
    for f in flats:
        if len(f) != n_arrays:
            raise PlanStructureMismatch("flat array count mismatch")
    sentinel = stacked_nd1 - 1
    stacked: List[np.ndarray] = []
    for i, kind in enumerate(kinds):
        if kind == "x":
            # non-stackable node (e.g. the pallas tile kernel's 2-D
            # per-query tables) — the host per-shard path serves these
            raise PlanStructureMismatch("plan contains non-stackable arrays")
        parts = [f[i] for f in flats]
        # replicate shard 0 into unused device slots
        parts = parts + [parts[0]] * (n_devices - len(parts))
        if kind == "s" or parts[0].ndim == 0:
            stacked.append(np.stack([np.asarray(p) for p in parts]))
            continue
        if kind == "dense":
            tail = parts[0].shape[1:]
            out = np.zeros((n_devices, stacked_nd1) + tail, parts[0].dtype)
            for d, a in enumerate(parts):
                out[d, : a.shape[0]] = a
            stacked.append(out)
            continue
        max_shape = tuple(
            max(p.shape[j] for p in parts) for j in range(parts[0].ndim)
        )
        if kind == "d":
            out = np.full((n_devices,) + max_shape, sentinel,
                          dtype=parts[0].dtype)
        else:
            out = np.full((n_devices,) + max_shape, _PAD_VALUES[kind],
                          dtype=parts[0].dtype)
        for d, a in enumerate(parts):
            if kind == "d":
                # re-point the shard-local sentinel doc to the stacked
                # one (replicated filler slots came from shard 0)
                src_shard = d if d < len(plans) else 0
                a = np.where(a == local_nd_pads[src_shard], sentinel, a)
            out[(d,) + tuple(slice(0, s) for s in a.shape)] = a
        stacked.append(out)
    return stacked


def _strip_plan(p: PlanNode) -> PlanNode:
    """Structural clone with data arrays dropped.

    emit() reads data exclusively through ``ctx.take`` during tracing;
    only static attributes (kinds, relation, boost_mode, child lists,
    ``len(factor_columns)``) are consulted on ``self``. Caching the full
    template would pin up to maxsize copies of doc-sized numpy columns
    (e.g. FunctionScoreNode factor columns) for the process lifetime."""
    import copy

    q = copy.copy(p)
    for name, val in vars(q).items():
        if isinstance(val, np.ndarray) and val.size > 8:
            setattr(q, name, None)
        elif isinstance(val, PlanNode):
            setattr(q, name, _strip_plan(val))
        elif isinstance(val, list) and val:
            if all(isinstance(v, PlanNode) for v in val):
                setattr(q, name, [_strip_plan(c) for c in val])
            elif all(isinstance(v, np.ndarray) for v in val):
                # length is trace-relevant (ctx.take count); contents not
                setattr(q, name, [None] * len(val))
    return q


class _TemplateHolder:
    """lru_cache key: plan structure + stacked shapes; holds an
    array-stripped template plan whose emit() defines the trace (same
    pattern as plan.py)."""

    __slots__ = ("plan", "_key")

    def __init__(self, plan: PlanNode, key: str):
        self.plan = plan
        self._key = key

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, _TemplateHolder) and self._key == other._key


@functools.lru_cache(maxsize=128)
def _mesh_query_program(mesh: Mesh, holder: _TemplateHolder, k: int):
    plan = holder.plan
    n_dev = mesh.devices.size

    def per_device(seg, plan_arrays):
        seg = {name: a[0] for name, a in seg.items()}
        ctx = EmitCtx(seg, [a[0] for a in plan_arrays])
        scores, matched = plan.emit(ctx)
        matched = matched & seg["live1"]
        total = jax.lax.psum(jnp.sum(matched.astype(jnp.int32)), "shards")
        masked = jnp.where(matched, scores, -jnp.inf)
        kk = min(k, masked.shape[0])
        loc_scores, loc_docs = jax.lax.top_k(masked, kk)
        # global merge over ICI: every device holds the same global top-k.
        # The merged pool holds n_dev*kk candidates, so the global cut is
        # min(k, pool) — NOT kk: when k exceeds one shard's padded doc
        # count, hits beyond the largest shard are still real.
        all_scores = jax.lax.all_gather(loc_scores, "shards").reshape(-1)
        all_docs = jax.lax.all_gather(loc_docs, "shards").reshape(-1)
        top_scores, top_idx = jax.lax.top_k(
            all_scores, min(k, all_scores.shape[0]))
        top_shard = (top_idx // kk).astype(jnp.int32)
        top_doc = all_docs[top_idx]
        return (top_scores[None], top_shard[None], top_doc[None],
                total[None])

    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(PS("shards"), PS("shards")),
        out_specs=(PS("shards"),) * 4,
        check_vma=False,
    )

    @jax.jit
    def run(seg, plan_arrays):
        outs = mapped(seg, plan_arrays)
        # merge is replicated: row 0 == row i
        return tuple(o[0] for o in outs)

    return run


def _shapes_sig(arrays) -> str:
    return ";".join(f"{a.shape}{a.dtype}" for a in arrays)


class IndexMeshSearch:
    """Routes an index's production query phase through the mesh.

    Owned by IndexService. Eligible searches (plain query + top-k by
    score) run as ONE multi-device program over all (shard, segment)
    pairs; anything the program doesn't cover yet returns None and the
    caller uses the host-merge path — same shape as the reference
    choosing between query-then-fetch variants per request.

    Staging is cached against the identity of the segment set and
    invalidated automatically when any shard refreshes/merges."""

    # request keys the mesh program does not cover (yet) — presence of
    # any of them falls back to the host path
    UNSUPPORTED = ("sort", "collapse", "rescore", "search_after", "slice",
                   "post_filter", "min_score", "terminate_after", "profile",
                   "aggs", "aggregations", "suggest", "highlight")

    def __init__(self, index_service, mesh: Optional[Mesh] = None):
        self.svc = index_service
        self._mesh = mesh
        self._executor: Optional[MeshPlanExecutor] = None
        self._staged_key = None
        self._pairs: List[Tuple[int, object]] = []  # (shard_id, segment)
        self.query_total = 0

    def _mesh_or_default(self) -> Mesh:
        if self._mesh is None:
            from elasticsearch_tpu.parallel.mesh import shard_mesh

            self._mesh = shard_mesh()
        return self._mesh

    def _current_pairs(self) -> List[Tuple[int, object]]:
        pairs = []
        for sid in sorted(self.svc.shards):
            eng = self.svc.shards[sid].engine
            for seg in eng.searchable_segments():
                if seg.num_docs > 0:
                    pairs.append((sid, seg))
        return pairs

    def _ensure_staged(self) -> bool:
        pairs = self._current_pairs()
        if not pairs:
            return False
        mesh = self._mesh_or_default()
        if len(pairs) > mesh.devices.size:
            return False
        # live_doc_count participates: deletes mutate a sealed segment's
        # live mask in place, which must invalidate the staged live1
        key = tuple((sid, id(seg), seg.live_doc_count) for sid, seg in pairs)
        if key != self._staged_key:
            self._executor = MeshPlanExecutor([seg for _, seg in pairs],
                                              mesh)
            self._pairs = pairs
            self._staged_key = key
        return True

    def query(self, body: dict, k: int):
        """Returns (total, refs, max_score) or None if ineligible."""
        from elasticsearch_tpu.search.query_dsl import (
            ShardQueryContext,
            parse_query,
        )
        from elasticsearch_tpu.search.service import DocRef

        body = body or {}
        if any(body.get(key) is not None for key in self.UNSUPPORTED):
            return None
        if len(self.svc.shards) < 2:
            return None  # single shard: host path is already one program
        if any(getattr(self.svc.shards[s].engine, "index_sort", None)
               for s in self.svc.shards):
            return None  # index-sorted early termination beats top-k
        if not self._ensure_staged():
            return None
        qb = parse_query(body.get("query"))
        try:
            plans = []
            for sid, seg in self._pairs:
                shard = self.svc.shards[sid]
                ctx = ShardQueryContext(shard.mapper_service,
                                        engine=shard.engine)
                # mesh plans must stack across shards; the pallas tile
                # node is non-stackable, so pin the scatter nodes here
                ctx.for_mesh = True
                plans.append(qb.to_plan(ctx, seg))
            scores, slots, docs, total = self._executor.execute(plans, k)
        except PlanStructureMismatch:
            return None
        except NotImplementedError:
            return None  # a builder without a plan form
        self.query_total += 1
        refs = []
        max_score = None
        for s, slot, d in zip(scores, slots, docs):
            if s == -np.inf:
                continue
            sid, seg = self._pairs[int(slot)]
            refs.append(DocRef(sid, seg.name, int(d), float(s)))
            if max_score is None:
                max_score = float(s)
        return int(total), refs, max_score


class MeshPlanExecutor:
    """Stage N shard segments onto an N-device mesh once; run any query
    plan as one compiled multi-device program.

    ``segments``: one sealed segment per shard (the staging unit — a shard
    with several NRT segments is force-merged or served by the host path
    until its next seal)."""

    def __init__(self, segments: List, mesh: Optional[Mesh] = None):
        from elasticsearch_tpu.parallel.distributed import stack_shard_arrays
        from elasticsearch_tpu.parallel.mesh import shard_mesh

        self.mesh = mesh or shard_mesh()
        self.n_dev = self.mesh.devices.size
        self.segments = segments
        stacked = stack_shard_arrays(segments, self.n_dev)
        self.nd_pad = stacked.pop("nd_pad")
        self.nd1 = self.nd_pad + 1
        sharding = NamedSharding(self.mesh, PS("shards"))
        self._seg_staged = {
            name: jax.device_put(arr, sharding)
            for name, arr in stacked.items()
        }
        self._sharding = sharding

    def execute(self, plans: List[PlanNode], k: int):
        """plans: one per shard, same query. Returns
        (top_scores [k], top_shard [k], top_doc [k], total) as numpy/int —
        doc ids are in the STACKED doc space (valid per-shard ids since
        every shard zero-bases)."""
        if len(plans) != len(self.segments):
            raise ValueError("one plan per staged shard required")
        local_pads = [s.nd_pad for s in self.segments]
        stacked = stack_plans(plans, local_pads, self.nd1, self.n_dev)
        key = (plans[0].key() + "|" + _shapes_sig(stacked)
               + f"|k{k}|n{self.n_dev}")
        run = _mesh_query_program(
            self.mesh, _TemplateHolder(_strip_plan(plans[0]), key), k)
        staged_plan = [jax.device_put(a, self._sharding) for a in stacked]
        top_scores, top_shard, top_doc, total = run(self._seg_staged,
                                                    staged_plan)
        return (np.asarray(top_scores), np.asarray(top_shard),
                np.asarray(top_doc), int(total))
