"""Distributed query execution over a device mesh with ICI collectives.

The reference's cross-shard search is an RPC scatter-gather
(action/search/AbstractSearchAsyncAction + SearchTransportService,
"indices:data/read/search[phase/query]" fan-out, then
SearchPhaseController.sortDocs/TopDocs.merge on the coordinator). Here, for
shards living on one TPU slice, the whole scatter-gather is ONE compiled
program (SURVEY.md §5.7/§5.8):

  shard_map over mesh axis "shards":
    per-device: BM25 scatter-add scoring over the local shard's postings
                -> local lax.top_k
    collective: all_gather(topk) over ICI -> every device holds the global
                candidate set -> final lax.top_k  (the "TopDocs.merge")
    agg partials (counts/sums/histograms/HLL registers) -> psum over ICI

Shards are stacked to identical padded shapes (power-of-two buckets from
segment seal) so one program serves every shard — the mesh dimension is
just a leading axis.

DFS-stats mode (distributed IDF; search/dfs/DfsPhase.java:45): term df and
doc counts are psum'd across shards before weights are computed, giving
identical scores to a single-shard index — the reference needs an extra
network round-trip for this; here it is one collective in the same program.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from elasticsearch_tpu.parallel.compat import shard_map

from elasticsearch_tpu.ops.scoring import B, K1, bm25_idf


# ---------------------------------------------------------------------------
# Stacking shards to a uniform shape
# ---------------------------------------------------------------------------


def stack_shard_arrays(segments: List, n_devices: int) -> Dict[str, np.ndarray]:
    """Stack one segment per shard into mesh-ready arrays.

    All shards pad to the max bucketed shape. Returns host numpy arrays
    with a leading [n_devices] axis.
    """
    if len(segments) > n_devices:
        raise ValueError(f"{len(segments)} shards > {n_devices} devices")
    nd_pad = max(s.nd_pad for s in segments)
    n_blocks = max(s.block_docs.shape[0] for s in segments)
    n_norm = max(s.norms.shape[0] for s in segments)
    blk = segments[0].block_docs.shape[1]

    block_docs = np.full((n_devices, n_blocks, blk), nd_pad, dtype=np.int32)
    block_tfs = np.zeros((n_devices, n_blocks, blk), dtype=np.float32)
    norms = np.ones((n_devices, n_norm, nd_pad + 1), dtype=np.float32)
    live1 = np.zeros((n_devices, nd_pad + 1), dtype=bool)
    for i, seg in enumerate(segments):
        bd = seg.block_docs.copy()
        bd[bd == seg.nd_pad] = nd_pad  # re-point sentinel to stacked pad
        block_docs[i, : bd.shape[0]] = bd
        block_tfs[i, : seg.block_tfs.shape[0]] = seg.block_tfs
        # norms columns beyond the segment's own nd_pad stay 1
        norms[i, : seg.norms.shape[0], : seg.norms.shape[1] - 1] = seg.norms[:, :-1]
        norms[i, :, nd_pad] = 1.0
        live1[i, : seg.live.shape[0]] = seg.live
    return {
        "block_docs": block_docs,
        "block_tfs": block_tfs,
        "norms": norms,
        "live1": live1,
        "nd_pad": nd_pad,
    }


def stack_query_arrays(segments: List, n_devices: int, field: str,
                       terms: List[str], qb_pad: int = 8) -> Dict[str, np.ndarray]:
    """Per-shard gather arrays for the same logical query (term ids differ
    per shard). Weights are left as *local* df/doc_count fractions when DFS
    mode is on — the kernel computes global idf after the psum."""
    qb = qb_pad
    per_shard = []
    for seg in segments:
        blocks, rows, avgdls, dfs = [], [], [], []
        term_slots = []
        for ti, t in enumerate(terms):
            tid = seg.term_id(field, t)
            if tid < 0:
                continue
            start = int(seg.term_block_start[tid])
            for bi in range(start, start + int(seg.term_block_count[tid])):
                blocks.append(bi)
                rows.append(seg.field_norm_idx.get(field, 0))
                avgdls.append(seg.field_avgdl(field))
                dfs.append(int(seg.term_doc_freq[tid]))
                term_slots.append(ti)
        per_shard.append((blocks, rows, avgdls, dfs, term_slots))
        qb = max(qb, len(blocks))
    n = 1
    while n < qb:
        n *= 2
    T = len(terms)
    out = {
        "q_blocks": np.zeros((n_devices, n), np.int32),
        "q_norm_rows": np.zeros((n_devices, n), np.int32),
        "q_avgdl": np.ones((n_devices, n), np.float32),
        "q_valid": np.zeros((n_devices, n), bool),
        "q_term_slot": np.zeros((n_devices, n), np.int32),
        # per-shard term stats for DFS psum: [n_devices, T]
        "term_df": np.zeros((n_devices, T), np.float32),
        "field_doc_count": np.zeros((n_devices, 1), np.float32),
        "field_sum_ttf": np.zeros((n_devices, 1), np.float32),
    }
    for i, seg in enumerate(segments):
        blocks, rows, avgdls, dfs, term_slots = per_shard[i]
        L = len(blocks)
        out["q_blocks"][i, :L] = blocks
        out["q_norm_rows"][i, :L] = rows
        out["q_avgdl"][i, :L] = avgdls
        out["q_valid"][i, :L] = True
        out["q_term_slot"][i, :L] = term_slots
        for ti, t in enumerate(terms):
            tid = seg.term_id(field, t)
            if tid >= 0:
                out["term_df"][i, ti] = float(seg.term_doc_freq[tid])
        out["field_doc_count"][i, 0] = float(
            seg.field_stats.get(field, {}).get("doc_count", 0)
        )
        out["field_sum_ttf"][i, 0] = float(
            seg.field_stats.get(field, {}).get("sum_ttf", 0)
        )
    return out


# ---------------------------------------------------------------------------
# The distributed program
# ---------------------------------------------------------------------------


def build_distributed_search(mesh: Mesh, k: int, with_histogram: bool = False,
                             n_hist_buckets: int = 32):
    """Compile the full distributed query-phase program.

    Returns fn(shard_arrays, query_arrays[, hist_arrays]) ->
      (top_scores [k], top_shard [k], top_doc [k], total_hits scalar
       [, hist_counts [n_hist_buckets]])
    — all replicated outputs (every device computes the same merge, the
    idiomatic way to keep results on-device for a following phase).
    """
    n_dev = mesh.devices.size

    def per_shard(block_docs, block_tfs, norms, live1, q_blocks, q_norm_rows,
                  q_avgdl, q_valid, q_term_slot, term_df, field_doc_count,
                  field_sum_ttf, *hist_args):
        # drop the leading per-device axis of size 1 from shard_map blocks
        block_docs = block_docs[0]
        block_tfs = block_tfs[0]
        norms = norms[0]
        live1 = live1[0]
        q_blocks, q_norm_rows = q_blocks[0], q_norm_rows[0]
        q_avgdl, q_valid, q_term_slot = q_avgdl[0], q_valid[0], q_term_slot[0]
        term_df, field_doc_count = term_df[0], field_doc_count[0]
        field_sum_ttf = field_sum_ttf[0]

        # ---- DFS phase: global term + collection stats via psum ----
        # (DfsPhase.termStatistics + CollectionStatistics: df, docCount and
        # sumTotalTermFreq must be corpus-global for score parity)
        g_df = jax.lax.psum(term_df, "shards")  # [T]
        g_doc_count = jax.lax.psum(field_doc_count, "shards")  # [1]
        g_sum_ttf = jax.lax.psum(field_sum_ttf, "shards")  # [1]
        idf = jnp.log(1.0 + (g_doc_count[0] - g_df + 0.5) / (g_df + 0.5))
        q_weights = jnp.where(q_valid, idf[q_term_slot], 0.0).astype(jnp.float32)
        g_avgdl = jnp.maximum(g_sum_ttf[0] / jnp.maximum(g_doc_count[0], 1.0), 1.0)

        # ---- local scoring (the per-shard hot loop) ----
        docs = block_docs[q_blocks]
        tfs = block_tfs[q_blocks]
        nd1_ = norms.shape[1]
        flat_idx = (q_norm_rows[:, None] * nd1_ + docs).ravel()
        doc_len = norms.ravel()[flat_idx].reshape(docs.shape)
        del q_avgdl  # local avgdl replaced by the DFS-global value
        denom = tfs + K1 * (1.0 - B + B * doc_len / g_avgdl)
        matched_blk = (tfs > 0.0) & q_valid[:, None]
        contrib = jnp.where(
            matched_blk, q_weights[:, None] * tfs * (K1 + 1.0) / denom, 0.0
        )
        nd1 = norms.shape[1]
        scores = jnp.zeros((nd1,), jnp.float32).at[docs].add(contrib)
        counts = jnp.zeros((nd1,), jnp.float32).at[docs].add(
            matched_blk.astype(jnp.float32)
        )
        matched = (counts > 0) & live1
        total_local = jnp.sum(matched.astype(jnp.int32))

        # ---- local top-k ----
        masked = jnp.where(matched, scores, -jnp.inf)
        kk = min(k, masked.shape[0])
        loc_scores, loc_docs = jax.lax.top_k(masked, kk)

        # ---- global merge over ICI (TopDocs.merge analog) ----
        my_shard = jax.lax.axis_index("shards")
        all_scores = jax.lax.all_gather(loc_scores, "shards").reshape(-1)
        all_docs = jax.lax.all_gather(loc_docs, "shards").reshape(-1)
        shard_ids = jnp.repeat(jnp.arange(n_dev, dtype=jnp.int32), kk)
        top_scores, top_idx = jax.lax.top_k(all_scores, kk)
        top_shard = shard_ids[top_idx]
        top_doc = all_docs[top_idx]
        total = jax.lax.psum(total_local, "shards")

        outs = [top_scores[None], top_shard[None], top_doc[None], total[None]]
        if with_histogram:
            flat_docs, flat_vals, interval, offset = hist_args
            flat_docs, flat_vals = flat_docs[0], flat_vals[0]
            interval, offset = interval[0], offset[0]
            bucket = jnp.floor(
                (flat_vals - offset[0]) / interval[0]
            ).astype(jnp.int32)
            ok = matched[flat_docs] & (bucket >= 0) & (bucket < n_hist_buckets)
            bucket = jnp.clip(bucket, 0, n_hist_buckets - 1)
            local_hist = jnp.zeros((n_hist_buckets,), jnp.int32).at[bucket].add(
                ok.astype(jnp.int32)
            )
            outs.append(jax.lax.psum(local_hist, "shards")[None])
        return tuple(outs)

    n_in = 12 + (4 if with_histogram else 0)
    in_specs = tuple([PS("shards")] * n_in)
    n_out = 4 + (1 if with_histogram else 0)
    # outputs replicated: shard_map requires every output to carry the mesh
    # axis or be produced identically; we gather+merge on every device and
    # emit with a leading 1-sized shards slice, then take index 0
    out_specs = tuple([PS("shards")] * n_out)

    mapped = shard_map(per_shard, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)

    @jax.jit
    def run(*args):
        outs = mapped(*args)
        # every device computed the same merged result; row 0 == row i
        return tuple(o[0] for o in outs)

    return run


class DistributedSearcher:
    """Host-side wrapper: stage stacked shards once, run compiled searches.

    This is the "one slice" data plane. The cross-slice path (multiple
    hosts) reuses the ShardQueryResult merge in search/service.py over DCN
    — mirroring the reference's coordinator merge.
    """

    def __init__(self, segments: List, mesh: Optional[Mesh] = None):
        from elasticsearch_tpu.parallel.mesh import shard_mesh

        self.mesh = mesh or shard_mesh()
        self.n_dev = self.mesh.devices.size
        self.segments = segments
        self.shard_arrays = stack_shard_arrays(segments, self.n_dev)
        self._programs: Dict[Tuple, object] = {}
        self._staged = None

    def _stage(self):
        if self._staged is None:
            sharding = NamedSharding(self.mesh, PS("shards"))
            self._staged = {
                name: jax.device_put(arr, sharding)
                for name, arr in self.shard_arrays.items()
                if name != "nd_pad"
            }
        return self._staged

    def search(self, field: str, terms: List[str], k: int = 10):
        q = stack_query_arrays(self.segments, self.n_dev, field, terms)
        qb_shape = q["q_blocks"].shape
        key = (k, qb_shape, False)
        if key not in self._programs:
            self._programs[key] = build_distributed_search(self.mesh, k)
        run = self._programs[key]
        staged = self._stage()
        sharding = NamedSharding(self.mesh, PS("shards"))
        args = [
            staged["block_docs"], staged["block_tfs"], staged["norms"],
            staged["live1"],
        ] + [jax.device_put(q[n], sharding) for n in (
            "q_blocks", "q_norm_rows", "q_avgdl", "q_valid", "q_term_slot",
            "term_df", "field_doc_count", "field_sum_ttf",
        )]
        top_scores, top_shard, top_doc, total = run(*args)
        return (
            np.asarray(top_scores), np.asarray(top_shard),
            np.asarray(top_doc), int(total),
        )
