"""geoip + user_agent ingest processors.

Role models: ``plugins/ingest-geoip`` (GeoIpProcessor over a MaxMind
database) and ``plugins/ingest-user-agent`` (UserAgentProcessor over the
ua-parser regex set). Like the reference — whose MaxMind .mmdb ships as a
separate download — the geoip database here is pluggable: a small builtin
range table covers well-known public resolver/documentation ranges, and
``database_file`` points at a JSON list of
``{"cidr": ..., "country_iso_code": ..., ...}`` entries for real data.
"""

from __future__ import annotations

import ipaddress
import json
import re
from typing import List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentException

# builtin stand-in "database": well-known public ranges (documentation +
# public resolvers), enough to exercise every property end-to-end
_BUILTIN_DB = [
    {"cidr": "8.8.8.0/24", "country_iso_code": "US",
     "country_name": "United States", "continent_name": "North America",
     "city_name": "Mountain View", "region_name": "California",
     "location": {"lat": 37.386, "lon": -122.0838}, "timezone": "America/Los_Angeles"},
    {"cidr": "1.1.1.0/24", "country_iso_code": "AU",
     "country_name": "Australia", "continent_name": "Oceania",
     "city_name": "Sydney", "region_name": "New South Wales",
     "location": {"lat": -33.8688, "lon": 151.2093}, "timezone": "Australia/Sydney"},
    {"cidr": "81.2.69.0/24", "country_iso_code": "GB",
     "country_name": "United Kingdom", "continent_name": "Europe",
     "city_name": "London", "region_name": "England",
     "location": {"lat": 51.5142, "lon": -0.0931}, "timezone": "Europe/London"},
    {"cidr": "2001:4860:4860::/48", "country_iso_code": "US",
     "country_name": "United States", "continent_name": "North America",
     "location": {"lat": 37.751, "lon": -97.822}},
]

_DEFAULT_GEOIP_PROPS = ["continent_name", "country_iso_code", "region_name",
                        "city_name", "location"]

_db_cache: dict = {}


def _load_db(path: Optional[str]) -> List[tuple]:
    """Parsed [(network, entry)] list, cached per database (CIDR parsing
    happens once per db, never per document)."""
    key = path or "__builtin__"
    parsed = _db_cache.get(key)
    if parsed is None:
        if path is None:
            entries = _BUILTIN_DB
        else:
            with open(path, encoding="utf-8") as f:
                entries = json.load(f)
        parsed = _db_cache[key] = [
            (ipaddress.ip_network(e["cidr"]), e) for e in entries
        ]
    return parsed


def geoip_processor(cfg: dict, doc) -> None:
    """GeoIpProcessor: field (required), target_field (default 'geoip'),
    properties, ignore_missing."""
    field = cfg.get("field")
    if field is None:
        raise IllegalArgumentException("[geoip] [field] required property is missing")
    value = doc.get(field)
    if value is None:
        if cfg.get("ignore_missing"):
            return
        raise IllegalArgumentException(f"field [{field}] not present as part of path [{field}]")
    try:
        addr = ipaddress.ip_address(str(value))
    except ValueError as e:
        raise IllegalArgumentException(f"[geoip] '{value}' is not an IP string") from e
    nets = _load_db(cfg.get("database_file"))
    hit = None
    for net, entry in nets:
        if addr.version == net.version and addr in net:
            hit = entry
            break
    if hit is None:
        return  # unresolvable addresses add nothing (reference behavior)
    props = cfg.get("properties", _DEFAULT_GEOIP_PROPS)
    data = {p: hit[p] for p in props if p in hit}
    if data:
        doc.set(cfg.get("target_field", "geoip"), data)


# --- user agent ------------------------------------------------------------

_UA_BROWSERS = [
    # Edge + Opera carry a Chrome/ token too — they must match first
    ("Edge", re.compile(r"Edge?/(\d+)\.(\d+)")),
    ("Opera", re.compile(r"OPR/(\d+)\.(\d+)")),
    ("Chrome", re.compile(r"Chrome/(\d+)\.(\d+)")),
    ("Firefox", re.compile(r"Firefox/(\d+)\.(\d+)")),
    ("Safari", re.compile(r"Version/(\d+)\.(\d+).*Safari/")),
    ("IE", re.compile(r"MSIE (\d+)\.(\d+)")),
    ("IE", re.compile(r"Trident/.*rv:(\d+)\.(\d+)")),
    ("curl", re.compile(r"curl/(\d+)\.(\d+)")),
]

_UA_OS = [
    ("Windows 10", re.compile(r"Windows NT 10\.0")),
    ("Windows 7", re.compile(r"Windows NT 6\.1")),
    ("Windows", re.compile(r"Windows NT")),
    ("Android", re.compile(r"Android (\d+)")),
    ("iOS", re.compile(r"iPhone OS (\d+)|CPU OS (\d+)")),
    ("Mac OS X", re.compile(r"Mac OS X (\d+)[._](\d+)")),
    ("Linux", re.compile(r"Linux")),
]


def _parse_user_agent(ua: str) -> dict:
    out = {"name": "Other", "device": {"name": "Other"}}
    for name, rx in _UA_BROWSERS:
        m = rx.search(ua)
        if m:
            out["name"] = name
            groups = [g for g in m.groups() if g is not None]
            if groups:
                out["major"] = groups[0]
                if len(groups) > 1:
                    out["minor"] = groups[1]
                out["version"] = ".".join(groups[:2])
            break
    for os_name, rx in _UA_OS:
        m = rx.search(ua)
        if m:
            out["os"] = {"name": os_name, "full": os_name}
            groups = [g for g in m.groups() if g is not None]
            if groups:
                out["os"]["version"] = groups[0]
                out["os"]["full"] = f"{os_name} {groups[0]}"
            break
    if "Mobile" in ua or "iPhone" in ua or "Android" in ua:
        out["device"] = {"name": "Smartphone" if "iPhone" not in ua else "iPhone"}
    return out


def user_agent_processor(cfg: dict, doc) -> None:
    """UserAgentProcessor: field (required), target_field (default
    'user_agent'), properties, ignore_missing."""
    field = cfg.get("field")
    if field is None:
        raise IllegalArgumentException(
            "[user_agent] [field] required property is missing")
    value = doc.get(field)
    if value is None:
        if cfg.get("ignore_missing"):
            return
        raise IllegalArgumentException(
            f"field [{field}] not present as part of path [{field}]")
    parsed = _parse_user_agent(str(value))
    props = cfg.get("properties")
    if props:
        parsed = {k: v for k, v in parsed.items() if k in props}
    doc.set(cfg.get("target_field", "user_agent"), parsed)
