"""Ingest pipelines: document preprocessing before indexing.

Role model: ``IngestService``/``PipelineExecutionService``
(core/.../ingest/, ingest/PipelineExecutionService.java:71) + the common
processors from ``modules/ingest-common`` (set, remove, rename, convert,
lowercase/uppercase, trim, split, join, gsub, date, json, kv, script,
fail, drop-equivalent, append, grok-lite). Pipelines are stored in cluster
state and applied node-side on the write path (§3.3 of SURVEY.md).
"""

from __future__ import annotations

import datetime as _dt
import json
import re
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuException,
    IllegalArgumentException,
    ResourceNotFoundException,
)


class IngestProcessorException(ElasticsearchTpuException):
    status_code = 500


class IngestDocument:
    """Mutable doc view with dotted-path access + ingest metadata
    (ingest/IngestDocument.java)."""

    def __init__(self, source: dict, doc_id: Optional[str], index: Optional[str]):
        self.source = source
        self.meta = {"_id": doc_id, "_index": index}
        self.dropped = False

    def get(self, path: str, default=None):
        if path.startswith("_ingest."):
            if path == "_ingest.timestamp":
                return _dt.datetime.now(_dt.timezone.utc).isoformat()
        if path in self.meta:
            return self.meta[path]
        node = self.source
        for part in path.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def has(self, path: str) -> bool:
        sentinel = object()
        return self.get(path, sentinel) is not sentinel

    def set(self, path: str, value) -> None:
        if path in ("_id", "_index"):
            self.meta[path] = value
            return
        parts = path.split(".")
        node = self.source
        for p in parts[:-1]:
            nxt = node.get(p)
            if not isinstance(nxt, dict):
                nxt = {}
                node[p] = nxt
            node = nxt
        node[parts[-1]] = value

    def remove(self, path: str) -> None:
        parts = path.split(".")
        node = self.source
        for p in parts[:-1]:
            node = node.get(p)
            if not isinstance(node, dict):
                return
        node.pop(parts[-1], None)

    def render(self, template: str):
        """{{field}} template substitution (mustache-lite)."""
        def sub(m):
            v = self.get(m.group(1).strip())
            return "" if v is None else str(v)

        return re.sub(r"\{\{(.*?)\}\}", sub, template)


# ---------------------------------------------------------------------------
# Processors
# ---------------------------------------------------------------------------


def _p_set(cfg, doc: IngestDocument):
    field = cfg["field"]
    if not cfg.get("override", True) and doc.has(field):
        return
    value = cfg.get("value")
    if isinstance(value, str):
        value = doc.render(value)
    doc.set(field, value)


def _p_remove(cfg, doc):
    fields = cfg["field"]
    for f in fields if isinstance(fields, list) else [fields]:
        if not doc.has(f) and not cfg.get("ignore_missing", False):
            raise IngestProcessorException(f"field [{f}] not present as part of path [{f}]")
        doc.remove(f)


def _p_rename(cfg, doc):
    src, dst = cfg["field"], cfg["target_field"]
    if not doc.has(src):
        if cfg.get("ignore_missing", False):
            return
        raise IngestProcessorException(f"field [{src}] doesn't exist")
    doc.set(dst, doc.get(src))
    doc.remove(src)


def _p_convert(cfg, doc):
    field = cfg["field"]
    target = cfg.get("target_field", field)
    typ = cfg["type"]
    v = doc.get(field)
    if v is None:
        if cfg.get("ignore_missing", False):
            return
        raise IngestProcessorException(f"field [{field}] is null or missing")
    try:
        if typ == "integer":
            v = int(v)
        elif typ == "long":
            v = int(v)
        elif typ == "float" or typ == "double":
            v = float(v)
        elif typ == "boolean":
            v = str(v).lower() == "true"
        elif typ == "string":
            v = str(v)
        elif typ == "auto":
            for cast in (int, float):
                try:
                    v = cast(v)
                    break
                except (TypeError, ValueError):
                    continue
    except (TypeError, ValueError) as e:
        raise IngestProcessorException(
            f"unable to convert [{v}] to {typ}"
        ) from e
    doc.set(target, v)


def _p_case(upper: bool):
    def run(cfg, doc):
        f = cfg["field"]
        v = doc.get(f)
        if v is None:
            if cfg.get("ignore_missing", False):
                return
            raise IngestProcessorException(f"field [{f}] is null or missing")
        doc.set(cfg.get("target_field", f), str(v).upper() if upper else str(v).lower())

    return run


def _p_trim(cfg, doc):
    f = cfg["field"]
    v = doc.get(f)
    if v is not None:
        doc.set(cfg.get("target_field", f), str(v).strip())


def _p_split(cfg, doc):
    f = cfg["field"]
    v = doc.get(f)
    if v is None:
        if cfg.get("ignore_missing", False):
            return
        raise IngestProcessorException(f"field [{f}] is null or missing")
    doc.set(cfg.get("target_field", f), re.split(cfg["separator"], str(v)))


def _p_join(cfg, doc):
    f = cfg["field"]
    v = doc.get(f)
    if isinstance(v, list):
        doc.set(cfg.get("target_field", f), cfg["separator"].join(str(x) for x in v))


def _p_gsub(cfg, doc):
    f = cfg["field"]
    v = doc.get(f)
    if v is not None:
        doc.set(cfg.get("target_field", f),
                re.sub(cfg["pattern"], cfg["replacement"], str(v)))


def _p_append(cfg, doc):
    f = cfg["field"]
    v = doc.get(f)
    add = cfg["value"]
    add = add if isinstance(add, list) else [add]
    add = [doc.render(x) if isinstance(x, str) else x for x in add]
    if v is None:
        doc.set(f, list(add))
    elif isinstance(v, list):
        v.extend(add)
    else:
        doc.set(f, [v] + list(add))


def _p_json(cfg, doc):
    f = cfg["field"]
    v = doc.get(f)
    try:
        parsed = json.loads(v)
    except (TypeError, json.JSONDecodeError) as e:
        raise IngestProcessorException(f"field [{f}] is not valid JSON") from e
    if cfg.get("add_to_root", False) and isinstance(parsed, dict):
        doc.source.update(parsed)
    else:
        doc.set(cfg.get("target_field", f), parsed)


def _p_kv(cfg, doc):
    f = cfg["field"]
    v = doc.get(f)
    if v is None:
        return
    target = cfg.get("target_field")
    for pair in str(v).split(cfg["field_split"]):
        if cfg["value_split"] in pair:
            k, val = pair.split(cfg["value_split"], 1)
            doc.set(f"{target}.{k}" if target else k, val)


def _p_date(cfg, doc):
    from elasticsearch_tpu.mapper.field_types import format_epoch_millis, parse_date

    f = cfg["field"]
    v = doc.get(f)
    formats = cfg.get("formats") or ["ISO8601"]
    millis = None
    for fmt in formats:
        try:
            if fmt in ("ISO8601", "UNIX", "UNIX_MS", "epoch_millis"):
                millis = parse_date(v)
                if fmt == "UNIX":
                    millis = int(float(v) * 1000)
            else:
                millis = parse_date(v, [fmt])
            break
        except Exception:
            continue
    if millis is None:
        raise IngestProcessorException(
            f"unable to parse date [{v}] with formats {formats}"
        )
    doc.set(cfg.get("target_field", "@timestamp"), format_epoch_millis(millis))


def _p_fail(cfg, doc):
    raise IngestProcessorException(doc.render(cfg.get("message", "Fail processor executed")))


def _p_drop(cfg, doc):
    doc.dropped = True


def _p_dot_expander(cfg, doc):
    f = cfg["field"]
    if f in doc.source and "." in f:
        v = doc.source.pop(f)
        doc.set(f, v)


_GROK_PATTERNS = {
    "WORD": r"\w+",
    "NUMBER": r"(?:[+-]?(?:\d+(?:\.\d+)?))",
    "INT": r"[+-]?\d+",
    "IP": r"\d{1,3}\.\d{1,3}\.\d{1,3}\.\d{1,3}",
    "DATA": r".*?",
    "GREEDYDATA": r".*",
    "NOTSPACE": r"\S+",
    "SPACE": r"\s*",
    "USERNAME": r"[a-zA-Z0-9._-]+",
    "TIMESTAMP_ISO8601": r"\d{4}-\d{2}-\d{2}[T ]\d{2}:\d{2}:\d{2}(?:\.\d+)?(?:Z|[+-]\d{2}:?\d{2})?",
    "LOGLEVEL": r"(?:DEBUG|INFO|WARN|ERROR|FATAL|TRACE)",
    "HTTPMETHOD": r"(?:GET|POST|PUT|DELETE|HEAD|OPTIONS|PATCH)",
}


def _grok_to_regex(pattern: str):
    """-> (regex string, {group_name: type}) — supports %{NAME:field:type}."""
    types: dict = {}

    def sub(m):
        name, field, typ = m.group(1), m.group(3), m.group(5)
        base = _GROK_PATTERNS.get(name)
        if base is None:
            raise IllegalArgumentException(f"Unable to find pattern [{name}] in Grok's pattern dictionary")
        if field:
            group = field.replace(".", "__DOT__")
            if typ:
                types[group] = typ
            return f"(?P<{group}>{base})"
        return f"(?:{base})"

    return re.sub(r"%\{(\w+)(:([\w.]+?))?(:(\w+))?\}", sub, pattern), types


def _p_grok(cfg, doc):
    f = cfg["field"]
    v = doc.get(f)
    if v is None:
        if cfg.get("ignore_missing", False):
            return
        raise IngestProcessorException(f"field [{f}] is null or missing")
    for pattern in cfg["patterns"]:
        regex, types = _grok_to_regex(pattern)
        m = re.compile(regex).search(str(v))
        if m:
            for name, val in m.groupdict().items():
                if val is None:
                    continue
                typ = types.get(name)
                if typ == "int":
                    val = int(float(val))
                elif typ == "float":
                    val = float(val)
                doc.set(name.replace("__DOT__", "."), val)
            return
    raise IngestProcessorException(f"Provided Grok expressions do not match field value: [{v}]")


def _p_uppercase(cfg, doc):
    _p_case(True)(cfg, doc)


def _p_script(cfg, doc):
    """Script processor (ingest/common/ScriptProcessor.java): the painless
    script mutates ``ctx`` in place. Like the reference's
    getSourceAndMetadata, ctx exposes the source AND the _index/_id
    metadata keys; metadata writes flow back to the document metadata,
    not into the stored source."""
    from elasticsearch_tpu.script.expression import compile_script

    # accept both config shapes: {source, lang, params} inline, or the
    # nested {"script": {source, lang, params}} form
    nested = cfg.get("script") if isinstance(cfg.get("script"), dict) else {}
    spec = {k: v for k, v in {**nested, **cfg}.items()
            if k in ("source", "inline", "lang", "id")}
    params = cfg.get("params") or nested.get("params") or {}
    script = compile_script(spec)
    run = getattr(script, "run", None)
    if run is None:  # numeric expression engine: no ctx mutation surface
        raise IngestProcessorException(
            "script processor requires a painless script")
    ctx = doc.source
    saved = {k: ctx.get(k) for k in ("_index", "_id") if k in ctx}
    ctx.update(doc.meta)
    try:
        run({"ctx": ctx, "params": dict(params)})
    finally:
        for k in ("_index", "_id"):
            value = ctx.pop(k, None)
            if value != doc.meta.get(k):
                doc.meta[k] = value
        ctx.update(saved)  # a source field literally named _index/_id


PROCESSORS = {
    "script": _p_script,
    "set": _p_set,
    "remove": _p_remove,
    "rename": _p_rename,
    "convert": _p_convert,
    "lowercase": _p_case(False),
    "uppercase": _p_case(True),
    "trim": _p_trim,
    "split": _p_split,
    "join": _p_join,
    "gsub": _p_gsub,
    "append": _p_append,
    "json": _p_json,
    "kv": _p_kv,
    "date": _p_date,
    "fail": _p_fail,
    "drop": _p_drop,
    "dot_expander": _p_dot_expander,
    "grok": _p_grok,
}

# geoip + user_agent ship as plugins in the reference (ingest-geoip,
# ingest-user-agent); registered here as always-available processors
from elasticsearch_tpu.ingest.geo_ua import (  # noqa: E402
    geoip_processor,
    user_agent_processor,
)

PROCESSORS["geoip"] = geoip_processor
PROCESSORS["user_agent"] = user_agent_processor


class Pipeline:
    def __init__(self, pipeline_id: str, body: dict):
        self.pipeline_id = pipeline_id
        self.description = body.get("description", "")
        self.processors = body.get("processors") or []
        self.on_failure = body.get("on_failure") or []
        for proc in self.processors:
            ((ptype, _),) = proc.items()
            if ptype not in PROCESSORS:
                raise IllegalArgumentException(
                    f"No processor type exists with name [{ptype}]"
                )

    def run(self, doc: IngestDocument) -> IngestDocument:
        for proc in self.processors:
            ((ptype, cfg),) = proc.items()
            try:
                PROCESSORS[ptype](cfg or {}, doc)
                if doc.dropped:
                    return doc
            except Exception as e:
                handlers = (cfg or {}).get("on_failure") or self.on_failure
                if not handlers and not (cfg or {}).get("ignore_failure"):
                    raise
                doc.set("_ingest.on_failure_message", str(e))
                for h in handlers:
                    ((htype, hcfg),) = h.items()
                    PROCESSORS[htype](hcfg or {}, doc)
        return doc


class IngestService:
    def __init__(self, node):
        self.node = node

    def put_pipeline(self, pipeline_id: str, body: dict) -> dict:
        Pipeline(pipeline_id, body)  # validate

        def update(state):
            new = state.copy()
            new.ingest_pipelines[pipeline_id] = body
            return new

        self.node.cluster_service.submit_state_update_task(
            f"put-pipeline [{pipeline_id}]", update
        )
        return {"acknowledged": True}

    def get_pipeline(self, pipeline_id: Optional[str] = None) -> dict:
        pipelines = self.node.cluster_service.state.ingest_pipelines
        if pipeline_id in (None, "*", "_all"):
            return dict(pipelines)
        if pipeline_id not in pipelines:
            raise ResourceNotFoundException(f"pipeline [{pipeline_id}] is missing")
        return {pipeline_id: pipelines[pipeline_id]}

    def delete_pipeline(self, pipeline_id: str) -> dict:
        if pipeline_id not in self.node.cluster_service.state.ingest_pipelines:
            raise ResourceNotFoundException(f"pipeline [{pipeline_id}] is missing")

        def update(state):
            new = state.copy()
            new.ingest_pipelines.pop(pipeline_id, None)
            return new

        self.node.cluster_service.submit_state_update_task(
            f"delete-pipeline [{pipeline_id}]", update
        )
        return {"acknowledged": True}

    def run_pipeline(self, pipeline_id: str, source: dict, doc_id, index) -> Optional[dict]:
        body = self.node.cluster_service.state.ingest_pipelines.get(pipeline_id)
        if body is None:
            raise IllegalArgumentException(f"pipeline with id [{pipeline_id}] does not exist")
        doc = IngestDocument(dict(source), doc_id, index)
        Pipeline(pipeline_id, body).run(doc)
        if doc.dropped:
            return None
        return doc.source

    def simulate(self, body: dict) -> dict:
        """_ingest/pipeline/_simulate."""
        pipeline_body = body.get("pipeline")
        if pipeline_body is None:
            pid = body.get("id")
            pipeline_body = self.get_pipeline(pid)[pid]
        pipeline = Pipeline("_simulate", pipeline_body)
        docs_out = []
        for d in body.get("docs", []):
            doc = IngestDocument(dict(d.get("_source", {})), d.get("_id"), d.get("_index"))
            try:
                pipeline.run(doc)
                docs_out.append({"doc": {
                    "_source": doc.source,
                    "_id": doc.meta.get("_id"),
                    "_index": doc.meta.get("_index"),
                }})
            except Exception as e:
                docs_out.append({"error": {"type": type(e).__name__, "reason": str(e)}})
        return {"docs": docs_out}
