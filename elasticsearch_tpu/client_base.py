"""In-process client: the typed facade over the node's APIs.

Role model: ``NodeClient`` (core/.../client/node/NodeClient.java) — same
process, no HTTP; plus a thin ``RestClient`` for tests exercising the wire
path. Method names follow the reference's high-level client surface
(index, get, delete, update, search, bulk, indices.*, cluster.*).
"""

from __future__ import annotations

import json
from typing import Optional

from elasticsearch_tpu.node import Node
from elasticsearch_tpu.rest.controller import RestController


class Client:
    """Direct (in-process) client — dispatches through the REST controller
    so request/response shapes match the wire exactly."""

    def __init__(self, node: Node):
        self.node = node
        self.controller = RestController(node)
        node.rest_controller = self.controller

    def perform(self, method: str, path: str, params: Optional[dict] = None,
                body=None, headers: Optional[dict] = None):
        if body is None:
            raw = b""
        elif isinstance(body, (bytes, str)):
            raw = body.encode() if isinstance(body, str) else body
        else:
            raw = json.dumps(body).encode()
        status, payload = self.controller.dispatch(
            method, path, {k: str(v) for k, v in (params or {}).items()}, raw,
            headers=headers,
        )
        return status, payload

    # --- document ---

    def index(self, index, doc_id, body, **params):
        if doc_id is None:
            return self.perform("POST", f"/{index}/_doc", params, body)
        return self.perform("PUT", f"/{index}/_doc/{doc_id}", params, body)

    def get(self, index, doc_id, **params):
        return self.perform("GET", f"/{index}/_doc/{doc_id}", params)

    def delete(self, index, doc_id, **params):
        return self.perform("DELETE", f"/{index}/_doc/{doc_id}", params)

    def update(self, index, doc_id, body, **params):
        return self.perform("POST", f"/{index}/_update/{doc_id}", params, body)

    def bulk(self, operations: str, **params):
        return self.perform("POST", "/_bulk", params, operations)

    def search(self, index="_all", body=None, **params):
        return self.perform("POST", f"/{index}/_search", params, body or {})

    def count(self, index="_all", body=None, **params):
        return self.perform("POST", f"/{index}/_count", params, body or {})
