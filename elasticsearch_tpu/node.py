"""Node: the composition root.

Role model: ``Node`` (core/.../node/Node.java:246) — wires settings,
cluster service, indices service, ingest, snapshots, tasks; plus the
index-lifecycle parts of ``IndicesService``/``MetaDataCreateIndexService``
(auto-create, templates, aliases) and the coordination-level APIs
(bulk, mget, msearch, scroll) that live under action/ in the reference.
"""

from __future__ import annotations

import os
import threading
import time
import uuid as _uuid
from typing import Dict, List, Optional

from elasticsearch_tpu.cluster.state import (
    ClusterService,
    ClusterState,
    DiscoveryNode,
    IndexMetadata,
    cluster_health,
)
from elasticsearch_tpu.common.errors import (
    ActionRequestValidationException,
    IllegalArgumentException,
    IndexAlreadyExistsException,
    IndexNotFoundException,
    InvalidIndexNameException,
    ResourceNotFoundException,
)
from elasticsearch_tpu.common import monitor
from elasticsearch_tpu.common.settings import (
    CLUSTER_NAME,
    NODE_NAME,
    PATH_DATA,
    Settings,
    cluster_settings,
    index_scoped_settings,
)
from elasticsearch_tpu.index.index_service import IndexService
from elasticsearch_tpu.ingest.pipeline import IngestService
from elasticsearch_tpu.tasks.task_manager import TaskManager
from elasticsearch_tpu.version import __version__

_INVALID_INDEX_CHARS = set(' "*\\<>|,/?#')


class Node:
    def __init__(self, settings: Settings = Settings.EMPTY,
                 data_path: Optional[str] = None,
                 plugins: Optional[list] = None):
        self.settings = settings
        self.node_id = _uuid.uuid4().hex[:20]
        self.node_name = NODE_NAME.get(settings)
        self.cluster_settings = cluster_settings()
        self.index_scoped_settings = index_scoped_settings()
        # kernel DMA-buffering toggle: exported once at startup; the
        # pallas layer reads ES_TPU_PALLAS_TPS (see settings registry)
        from elasticsearch_tpu.common.settings import (
            SEARCH_PALLAS_TILES_PER_STEP,
        )

        # exported unconditionally: a later Node in the same process must
        # not inherit a previous Node's value through a stale env var
        # (the env var is process-global — the last-constructed Node wins)
        os.environ["ES_TPU_PALLAS_TPS"] = str(
            int(SEARCH_PALLAS_TILES_PER_STEP.get(settings)))
        # node-wide postings-codec default for the kernel staging
        # (search.pallas.postings_codec; per-index override via
        # index.search.pallas.postings_codec — docs/PRUNING.md)
        from elasticsearch_tpu.common.settings import (
            SEARCH_PALLAS_POSTINGS_CODEC,
        )

        os.environ["ES_TPU_PALLAS_CODEC"] = str(
            SEARCH_PALLAS_POSTINGS_CODEC.get(settings))
        # cross-query micro-batching knobs are DYNAMIC (docs/BATCHING.md):
        # a cluster-settings update must reach every index's live batcher
        # (an operator disabling batching mid-incident can't wait for a
        # restart) — apply_settings fires these on PUT _cluster/settings
        from elasticsearch_tpu.common.settings import (
            SEARCH_BATCH_ENABLED,
            SEARCH_BATCH_MAX_QUERIES,
            SEARCH_BATCH_WINDOW_MS,
        )

        def _batchers(apply):
            def consume(value):
                for svc in self.indices.values():
                    apply(svc._batcher, value)
            return consume

        self.cluster_settings.add_settings_update_consumer(
            SEARCH_BATCH_ENABLED,
            _batchers(lambda b, v: setattr(b, "enabled", bool(v))))
        self.cluster_settings.add_settings_update_consumer(
            SEARCH_BATCH_WINDOW_MS,
            _batchers(lambda b, v: setattr(b, "window_s",
                                           float(v) / 1000.0)))
        self.cluster_settings.add_settings_update_consumer(
            SEARCH_BATCH_MAX_QUERIES,
            _batchers(lambda b, v: setattr(b, "max_queries", int(v))))
        # (block-max pruning knobs are dynamic too, but they need
        # EXPLICITNESS — an override must clear when the cluster key is
        # removed so the index's own Settings win again — which the
        # value-only consumer callback can't see; put_cluster_settings
        # syncs svc.pruning_*_override from the committed merged
        # settings instead. docs/PRUNING.md)
        # device-staging retry knobs (search.staging.retry.* — ISSUE 10,
        # docs/RESILIENCE.md): seed the process-level config from the
        # node file and keep it live under PUT _cluster/settings (the
        # explicitness-aware clear is synced in put_cluster_settings)
        from elasticsearch_tpu.common.settings import (
            SEARCH_STAGING_RETRY_BACKOFF_MS,
            SEARCH_STAGING_RETRY_MAX_ATTEMPTS,
        )

        from elasticsearch_tpu.common.staging import configure_staging_retry

        configure_staging_retry(
            max_attempts=settings.get_int(
                "search.staging.retry.max_attempts",
                SEARCH_STAGING_RETRY_MAX_ATTEMPTS.default),
            backoff_ms=settings.get_float(
                "search.staging.retry.backoff_ms",
                SEARCH_STAGING_RETRY_BACKOFF_MS.default))
        self.cluster_settings.add_settings_update_consumer(
            SEARCH_STAGING_RETRY_MAX_ATTEMPTS,
            lambda v: configure_staging_retry(max_attempts=int(v)))
        self.cluster_settings.add_settings_update_consumer(
            SEARCH_STAGING_RETRY_BACKOFF_MS,
            lambda v: configure_staging_retry(backoff_ms=float(v)))
        self.data_path = data_path or PATH_DATA.get(settings)
        self.persistent_path = data_path is not None or "path.data" in settings
        # zero-downtime rollout (ISSUE 14, docs/RESILIENCE.md "Rollout &
        # drain"): enable JAX's persistent compilation cache
        # (search.compile.cache_path) and install the program-variant
        # registry persisted beside the store, so restart never pays a
        # query-path first compile. Like the ES_TPU_* exports, the
        # process-global registry follows the last-constructed Node.
        from elasticsearch_tpu.common import compile_cache as _cc

        cache_path = settings.get_str("search.compile.cache_path", "")
        if cache_path:
            _cc.configure_compile_cache(cache_path)
        if self.persistent_path:
            _cc.set_variant_registry(_cc.VariantRegistry(
                os.path.join(self.data_path, "_state",
                             "compile_variants.json")))
        self._draining = False
        # secure settings from the encrypted keystore (KeyStoreWrapper):
        # kept OUT of the displayed settings (filtered) — consumers read
        # node.secure_settings explicitly, like the reference's
        # SecureSettings surface
        self.secure_settings: Dict[str, str] = {}
        if self.persistent_path and os.path.isdir(self.data_path or ""):
            from elasticsearch_tpu.common.keystore import KeyStore

            ks = KeyStore.load_if_exists(
                self.data_path, os.environ.get("ES_TPU_KEYSTORE_PASS", ""))
            if ks is not None:
                self.secure_settings = ks.as_settings_dict()
        node = DiscoveryNode(self.node_id, self.node_name, "127.0.0.1:9300")
        initial = ClusterState(
            CLUSTER_NAME.get(settings),
            nodes={self.node_id: node},
            master_node_id=self.node_id,
        )
        self.cluster_service = ClusterService(initial)
        # named bounded executors (ThreadPool.java) — the REST layer runs
        # handler work on the action's pool; full queues reject with 429
        from elasticsearch_tpu.common.thread_pool import ThreadPool

        # search.queue.size bounds BOTH backpressure points the same way
        # (docs/OVERLOAD.md): the REST-layer search executor queue here
        # and each index's admission queue (search/admission.py) — and
        # a dynamic update below retargets the live pool too, so the
        # contract survives PUT _cluster/settings mid-incident
        self.thread_pool = ThreadPool(overrides={
            "search": {"queue_size": settings.get_int(
                "search.queue.size", 1000)}})
        from elasticsearch_tpu.common.breaker import configure_breaker_service

        # hierarchical memory circuit breakers (indices.breaker.*)
        self.breaker_service = configure_breaker_service(settings)
        # device-memory accountant budget (search.memory.hbm_budget_bytes,
        # ISSUE 9): the exact HBM staging ledger is wired in as the real
        # "accounting" breaker child; over budget, stagings LRU-evict then
        # demote to the host rung (never 429/5xx) — docs/OBSERVABILITY.md
        from elasticsearch_tpu.common.memory import memory_accountant

        memory_accountant().set_budget(
            settings.get_bytes("search.memory.hbm_budget_bytes", 0))
        self.indices: Dict[str, IndexService] = {}
        self.ingest = IngestService(self)
        self.tasks = TaskManager(self.node_id)
        from elasticsearch_tpu.snapshots.service import SnapshotsService

        self.snapshots = SnapshotsService(self)
        self.scrolls: Dict[str, dict] = {}
        self._scroll_lock = threading.Lock()
        # keep-alive reaper (SearchService's keepAliveReaper): expired
        # scroll contexts pin segment views + device arrays, so they must
        # be freed on TIME, not only when another scroll request arrives
        self._reaper_stop = threading.Event()
        self._reaper = threading.Thread(
            target=self._reap_expired_scrolls_loop,
            name=f"scroll-reaper[{self.node_name}]", daemon=True)
        self._reaper.start()
        self.start_time = time.time()
        self._closed = False
        from elasticsearch_tpu.transport.remote_cluster import (
            RemoteClusterService,
            register_node,
        )

        register_node(self)
        self.remote_clusters = RemoteClusterService(self, settings)
        from elasticsearch_tpu.plugins import PluginsService

        self.plugins_service = PluginsService(self, settings, plugins)
        self.plugins_service.on_node_start()
        if self.persistent_path:
            # GatewayMetaState analog: global metadata first (templates,
            # persistent settings, stored scripts, pipelines,
            # repositories — gateway/GatewayMetaState.java:61,117), THEN
            # per-index recovery, matching the reference's recovery order;
            # the applier keeps the on-disk copy current from here on
            self.cluster_service.add_applier(self._persist_global_meta)
            self._recover_global_meta()
            self._recover_indices_from_disk()
            # AOT variant warming (ISSUE 14): replay the recorded
            # program-variant lattice in the background, off the query
            # path — a warmed restart serves zero query-path first
            # compiles (the rolling-restart soak's headline invariant)
            if settings.get_bool("search.compile.warm_on_start", True):
                self._start_compile_warming()

    # ------------------------------------------------------------------
    # Index lifecycle (MetaDataCreateIndexService / MetaDataDeleteIndexService)
    # ------------------------------------------------------------------

    def _validate_index_name(self, name: str) -> None:
        if not name or name != name.lower():
            raise InvalidIndexNameException(name, "must be lowercase")
        if name.startswith(("_", "-", "+")):
            raise InvalidIndexNameException(name, "must not start with '_', '-', or '+'")
        if any(c in _INVALID_INDEX_CHARS for c in name):
            raise InvalidIndexNameException(name, "must not contain special characters")

    def _index_data_path(self, name: str) -> Optional[str]:
        if not self.persistent_path:
            return None
        return os.path.join(self.data_path, "indices", name)

    def create_index(self, name: str, body: Optional[dict] = None) -> dict:
        body = body or {}
        self._validate_index_name(name)
        if name in self.indices or any(
            name in md.aliases for md in self.cluster_service.state.indices.values()
        ):
            raise IndexAlreadyExistsException(name)
        settings = Settings.from_dict(
            body.get("settings") or {}).with_index_prefix()
        mappings = body.get("mappings") or {}
        mappings, doc_type = _unwrap_typed_mapping(mappings)
        aliases = {a: (spec or {}) for a, spec in (body.get("aliases") or {}).items()}

        # apply matching templates, lowest order first (MetaDataCreateIndexService)
        templates = sorted(
            (t for t in self.cluster_service.state.templates.values()
             if _template_matches(t, name)),
            key=lambda t: t.get("order", 0),
        )
        merged_settings = Settings.EMPTY
        merged_mappings: dict = {}
        for t in templates:
            merged_settings = merged_settings.merged_with(
                Settings.from_dict(t.get("settings") or {}).with_index_prefix()
            )
            t_map = t.get("mappings") or {}
            if "_doc" in t_map:
                t_map = t_map["_doc"]
            _merge_mapping_dicts(merged_mappings, t_map)
            for a, spec in (t.get("aliases") or {}).items():
                aliases.setdefault(a, spec or {})
        merged_settings = merged_settings.merged_with(settings)
        _merge_mapping_dicts(merged_mappings, mappings)
        # node-level micro-batching + pallas-plane config (search.batch.*
        # / search.pallas.* — node scope, docs/BATCHING.md +
        # docs/PRUNING.md) seeds each index at lowest precedence, with
        # the CURRENT dynamic cluster settings on top: an index created
        # after PUT _cluster/settings {search.batch.*, search.pallas.*}
        # must honor the live value, not the node file's (the update
        # consumers only reach batchers alive at update time; the pruning
        # knobs are re-read per query from the index's Settings map)
        state = self.cluster_service.state
        # (search.staging.retry.* deliberately NOT seeded per index: the
        # retry config is process-level — a create-time snapshot in the
        # index Settings would shadow later dynamic cluster updates)
        for prefix in ("search.batch.", "search.pallas.", "search.knn.",
                       "search.aggs.", "search.telemetry.",
                       "search.queue.", "search.admission.",
                       "search.drain.", "index.staging."):
            cluster_dynamic = state.persistent_settings.merged_with(
                state.transient_settings).filtered_by_prefix(prefix)
            merged_settings = self.settings.filtered_by_prefix(
                prefix).merged_with(cluster_dynamic).merged_with(
                merged_settings)

        self.index_scoped_settings.validate(merged_settings, allow_unknown=True)
        svc = IndexService(name, merged_settings, merged_mappings,
                           self._index_data_path(name))
        svc.doc_type = doc_type  # 6.x custom type name echoed in responses
        # an index created AFTER a cluster-level index.staging.* commit
        # must honor the live override like its older peers (the
        # put_cluster_settings sync only reaches indices alive then)
        from elasticsearch_tpu.common.settings import (
            INDEX_STAGING_COMPACT_THRESHOLD,
            INDEX_STAGING_DELTA_ENABLED,
        )

        committed = state.persistent_settings.merged_with(
            state.transient_settings)
        if committed.get(INDEX_STAGING_DELTA_ENABLED.key) is not None:
            svc.staging_delta_enabled_override = (
                INDEX_STAGING_DELTA_ENABLED.get(committed))
        if committed.get(INDEX_STAGING_COMPACT_THRESHOLD.key) is not None:
            svc.staging_compact_threshold_override = (
                INDEX_STAGING_COMPACT_THRESHOLD.get(committed))
        if self._draining:
            # an index created while the node drains (auto-create from a
            # straggling write) joins the drain: its searches get the
            # same clean 503 instead of silently serving on a node the
            # orchestrator believes is quiescing
            svc.admission.begin_drain()
        self.indices[name] = svc

        def update(state: ClusterState) -> ClusterState:
            new = state.copy()
            new.indices[name] = IndexMetadata(
                name, merged_settings, svc.mapping_dict(), aliases,
                creation_date=svc.creation_date,
            )
            return new

        self.cluster_service.submit_state_update_task(f"create-index [{name}]", update)
        return {"acknowledged": True, "shards_acknowledged": True, "index": name}

    def delete_index(self, expression: str,
                     ignore_unavailable: bool = False,
                     allow_no_indices: bool = True) -> dict:
        state = self.cluster_service.state
        alias_parts = set()
        for part in str(expression).split(","):
            for md in state.indices.values():
                if part and part in md.aliases:
                    if ignore_unavailable:
                        alias_parts.add(part)  # silently skipped (6.x)
                        break
                    raise IllegalArgumentException(
                        f"The provided expression [{part}] matches an "
                        f"alias, specify the corresponding concrete "
                        f"indices instead.")
        # wildcard patterns in a DELETE only expand over concrete index
        # names — a pattern matching only aliases is a no-op
        # (TransportDeleteIndexAction + IndicesOptions for destructive ops)
        import fnmatch as _fn

        names = []
        for p in str(expression).split(","):
            if not p or p in alias_parts:
                continue
            if "*" in p or p == "_all":
                pat = "*" if p == "_all" else p
                matched = [n for n in state.indices
                           if _fn.fnmatchcase(n, pat)]
                if not matched and not allow_no_indices:
                    # a dead wildcard fails the WHOLE request before any
                    # deletion (IndicesOptions.fromOptions strictness)
                    raise IndexNotFoundException(p)
                names.extend(matched)
            else:
                try:
                    names.extend(state.resolve_index_names(p))
                except IndexNotFoundException:
                    if not ignore_unavailable:
                        raise
        names = list(dict.fromkeys(names))
        if not names:
            if not allow_no_indices:
                raise IndexNotFoundException(str(expression))
            return {"acknowledged": True}
        for name in names:
            svc = self.indices.pop(name, None)
            if svc is not None:
                svc.close()
            if self.persistent_path:
                import shutil

                path = self._index_data_path(name)
                if path and os.path.exists(path):
                    shutil.rmtree(path, ignore_errors=True)

        def update(state: ClusterState) -> ClusterState:
            new = state.copy()
            for name in names:
                new.indices.pop(name, None)
            return new

        self.cluster_service.submit_state_update_task(f"delete-index {names}", update)
        return {"acknowledged": True}

    def close_index(self, expression: str) -> dict:
        names = self.cluster_service.state.resolve_index_names(expression)

        def update(state: ClusterState) -> ClusterState:
            new = state.copy()
            for n in names:
                new.indices[n].state = "close"
            return new

        self.cluster_service.submit_state_update_task(f"close-index {names}", update)
        return {"acknowledged": True}

    def open_index(self, expression: str) -> dict:
        names = self.cluster_service.state.resolve_index_names(expression)

        def update(state: ClusterState) -> ClusterState:
            new = state.copy()
            for n in names:
                new.indices[n].state = "open"
            return new

        self.cluster_service.submit_state_update_task(f"open-index {names}", update)
        return {"acknowledged": True}

    @staticmethod
    def _global_meta_slice(state: ClusterState) -> dict:
        """The durable global MetaData: everything a full-cluster restart
        must bring back that is not per-index (the reference persists it
        via MetaDataStateFormat atomic _state files —
        gateway/GatewayMetaState.java:61). Transient settings are
        deliberately NOT here: the reference drops them on full restart."""
        return {
            "templates": state.templates,
            "persistent_settings": state.persistent_settings.as_nested_dict(),
            "stored_scripts": state.stored_scripts,
            "ingest_pipelines": state.ingest_pipelines,
            "repositories": state.repositories,
        }

    def _persist_global_meta(self, old: ClusterState,
                             new: ClusterState) -> None:
        """Cluster-state applier: atomically rewrite the global _state
        file whenever a durable slice changed (MetaDataStateFormat's
        write-tmp-then-rename discipline)."""
        if not self.persistent_path:
            return
        import json

        payload = self._global_meta_slice(new)
        if old is not None and self._global_meta_slice(old) == payload:
            return
        state_dir = os.path.join(self.data_path, "_state")
        os.makedirs(state_dir, exist_ok=True)
        tmp = os.path.join(state_dir, "global-meta.json.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(state_dir, "global-meta.json"))

    def _recover_global_meta(self) -> None:
        """Boot-time restore of the global MetaData slice, re-driven
        through each component's normal write path so side effects
        (settings consumers, repository object construction, remote
        cluster registration) re-fire exactly as they did originally."""
        path = os.path.join(self.data_path, "_state", "global-meta.json")
        if not os.path.exists(path):
            return
        import json

        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if data.get("persistent_settings"):
            self.put_cluster_settings(
                {"persistent": data["persistent_settings"]})

        def update(state: ClusterState) -> ClusterState:
            new = state.copy()
            new.templates.update(data.get("templates") or {})
            new.stored_scripts.update(data.get("stored_scripts") or {})
            new.ingest_pipelines.update(data.get("ingest_pipelines") or {})
            return new

        self.cluster_service.submit_state_update_task(
            "recover global metadata", update)
        for name, body in (data.get("repositories") or {}).items():
            try:
                self.snapshots.put_repository(name, body)
            except Exception:  # noqa: BLE001 — e.g. missing plugin type
                # an unregisterable repository must not block node boot
                # (the reference logs and continues; snapshots into it
                # fail with repository-missing at use time)
                pass

    def _recover_indices_from_disk(self) -> None:
        """GatewayService analog: restore index metadata + shard data from
        the data path on startup (gateway/GatewayMetaState.java)."""
        root = os.path.join(self.data_path, "indices")
        if not os.path.isdir(root):
            return
        import json

        for name in sorted(os.listdir(root)):
            meta_path = os.path.join(root, name, "_meta.json")
            if not os.path.exists(meta_path):
                continue
            with open(meta_path, encoding="utf-8") as f:
                meta = json.load(f)
            settings = Settings(meta.get("settings", {}))
            svc = IndexService(name, settings, meta.get("mappings"),
                               self._index_data_path(name))
            self.indices[name] = svc

            def update(state: ClusterState, name=name, settings=settings,
                       svc=svc, meta=meta) -> ClusterState:
                new = state.copy()
                new.indices[name] = IndexMetadata(
                    name, settings, svc.mapping_dict(), meta.get("aliases", {}),
                )
                return new

            self.cluster_service.submit_state_update_task(f"recover [{name}]", update)

    def _persist_index_meta(self, name: str) -> None:
        if not self.persistent_path:
            return
        import json

        md = self.cluster_service.state.indices.get(name)
        svc = self.indices.get(name)
        if md is None or svc is None:
            return
        path = self._index_data_path(name)
        os.makedirs(path, exist_ok=True)
        tmp = os.path.join(path, "_meta.json.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({
                "settings": md.settings.as_dict(),
                "mappings": svc.mapping_dict(),
                "aliases": md.aliases,
            }, f)
        os.replace(tmp, os.path.join(path, "_meta.json"))

    # ------------------------------------------------------------------
    # Resolution helpers
    # ------------------------------------------------------------------

    def index_service(self, name: str, auto_create: bool = False) -> IndexService:
        state = self.cluster_service.state
        if name in self.indices:
            if state.indices.get(name) and state.indices[name].state == "close":
                raise IllegalArgumentException(f"index [{name}] is closed")
            return self.indices[name]
        for idx_name, md in state.indices.items():
            if name in md.aliases:
                return self.indices[idx_name]
        if auto_create:
            from elasticsearch_tpu.common.settings import ACTION_AUTO_CREATE_INDEX

            if ACTION_AUTO_CREATE_INDEX.get(self.settings):
                self.create_index(name)
                return self.indices[name]
        raise IndexNotFoundException(name)

    def resolve_search_indices(self, expression: str) -> List[IndexService]:
        state = self.cluster_service.state
        out: List[IndexService] = []
        seen = set()
        parts = [p for p in str(expression or "_all").split(",") if p]             or ["_all"]
        for part in parts:
            wildcard = "*" in part or part in ("_all", "")
            for n in state.resolve_index_names(part):
                if n in seen:
                    continue
                if state.indices[n].state != "open":
                    # wildcard EXPANSION skips closed indices, but a
                    # closed index named explicitly is a request error
                    # (IndexClosedException)
                    if wildcard:
                        continue
                    raise IllegalArgumentException(
                        f"closed index [{n}] - IndexClosedException")
                seen.add(n)
                out.append(self.indices[n])
        return out

    # ------------------------------------------------------------------
    # Document APIs
    # ------------------------------------------------------------------

    def index_doc(self, index: str, doc_id: Optional[str], source: dict,
                  routing: Optional[str] = None, refresh=None,
                  pipeline: Optional[str] = None,
                  wait_for_active_shards=None,
                  parent: Optional[str] = None, **kw) -> dict:
        if doc_id is not None:
            if doc_id == "":
                raise IllegalArgumentException(
                    "if _id is specified it must not be empty")
            if len(doc_id.encode("utf-8")) > 512:
                raise ActionRequestValidationException(
                    f"Validation Failed: 1: id is too long, must be no "
                    f"longer than 512 bytes but was: "
                    f"{len(doc_id.encode('utf-8'))};")
        svc = self.index_service(index, auto_create=True)
        if wait_for_active_shards is not None:
            self._check_active_shards(svc, wait_for_active_shards)
        if pipeline:
            source = self.ingest.run_pipeline(pipeline, source, doc_id, index)
            if source is None:  # dropped by pipeline
                return {"_index": index, "_id": doc_id, "result": "noop"}
        if doc_id is None:
            doc_id = _uuid.uuid4().hex[:20]
            kw.setdefault("op_type", "create")
        r = svc.index_doc(doc_id, source, routing, parent=parent, **kw)
        self._maybe_refresh(svc, refresh, doc_id=doc_id, routing=routing)
        self._maybe_update_mapping_meta(index)
        return r

    def _check_active_shards(self, svc: IndexService, wanted) -> None:
        """wait_for_active_shards gate (ActiveShardsObserver +
        TransportWriteAction): on this single-node topology the active
        count per shard is 1 (the started primary; replicas are
        unassigned), so a larger requirement fails like the reference's
        UnavailableShardsException timeout."""
        from elasticsearch_tpu.index.seqno import check_active_shards

        check_active_shards(wanted, 1, 1 + svc.num_replicas, f"[{svc.name}]")

    def _maybe_refresh(self, svc: IndexService, refresh,
                       doc_id=None, routing=None) -> None:
        """Write-op refresh policy (TransportWriteAction). A write's
        ``refresh=true`` refreshes ONLY the written shard — another
        shard's still-buffered deletes must not become visible as a side
        effect (the reference refreshes the shard the op ran on)."""
        if refresh in (True, "true", ""):
            if doc_id is not None:
                svc.shards[svc._route(doc_id, routing)].refresh()
            else:
                svc.refresh()
        elif refresh == "wait_for":
            # refresh=wait_for (RefreshListeners): block until the periodic
            # refresh makes the write visible; force one when the scheduler
            # is disabled (the listener-cap forced refresh analog)
            if not svc.refresh_interval or svc.refresh_interval <= 0:
                if doc_id is not None:
                    svc.shards[svc._route(doc_id, routing)].refresh()
                else:
                    svc.refresh()
                return
            import threading

            events = []
            for shard in svc.shards.values():
                ev = threading.Event()
                shard.engine.add_refresh_listener(ev.set)
                events.append(ev)
            deadline = svc.refresh_interval * 2 + 0.5
            for ev in events:
                if not ev.wait(deadline):
                    svc.refresh()
                    break

    def _maybe_update_mapping_meta(self, index: str) -> None:
        # dynamic mapping updates flow back into cluster state (the master
        # round-trip in §3.3 of SURVEY.md)
        svc = self.indices.get(index)
        if svc is None:
            return
        state = self.cluster_service.state
        md = state.indices.get(index)
        if md is not None and md.mappings != svc.mapping_dict():
            def update(st: ClusterState) -> ClusterState:
                new = st.copy()
                new.indices[index].mappings = svc.mapping_dict()
                new.indices[index].version += 1
                return new

            self.cluster_service.submit_state_update_task(
                f"update-mapping [{index}]", update
            )
            self._persist_index_meta(index)

    def get_doc(self, index: str, doc_id: str, routing=None,
                realtime=True, refresh=None) -> dict:
        svc = self.index_service(index)
        if refresh in (True, "true", ""):
            # GET ?refresh=true forces a refresh before reading
            svc.refresh()
        g = svc.get_doc(doc_id, routing, realtime=realtime)
        out = {
            "_index": svc.name,
            "_type": "_doc",
            "_id": doc_id,
            "found": g.found,
        }
        if g.found:
            out["_version"] = g.version
            out["_seq_no"] = g.seqno
            out["_source"] = g.source
            # the STORED routing (a parent-only write stores the parent
            # as routing); fall back to echoing the request param
            stored_routing = getattr(g, "routing", None)
            if stored_routing is not None:
                out["_routing"] = stored_routing
            elif routing is not None:
                out["_routing"] = routing
        return out

    def delete_doc(self, index: str, doc_id: str, routing=None, refresh=None, **kw) -> dict:
        svc = self.index_service(index)
        r = svc.delete_doc(doc_id, routing, **kw)
        self._maybe_refresh(svc, refresh, doc_id=doc_id, routing=routing)
        return r

    def update_doc(self, index: str, doc_id: str, body: dict, routing=None,
                   refresh=None, version=None) -> dict:
        # upserts auto-create the index like every other write
        # (TransportUpdateAction resolves through auto-create)
        auto = "upsert" in (body or {}) or (body or {}).get("doc_as_upsert")
        svc = self.index_service(index, auto_create=bool(auto))
        r = svc.update_doc(doc_id, body, routing, version=version)
        self._maybe_refresh(svc, refresh, doc_id=doc_id, routing=routing)
        self._maybe_update_mapping_meta(index)
        return r

    def mget(self, body: dict, default_index: Optional[str] = None,
             default_type: Optional[str] = None, realtime: bool = True,
             refresh=None, stored_fields=None) -> dict:
        specs = body.get("docs")
        if specs is None and "ids" in body:
            # short form: {"ids": [...]} against the URL's index
            specs = [{"_id": i} for i in body["ids"]]
        # whole-request validation (MultiGetRequest.validate): any bad
        # item fails the REQUEST, not just the item
        problems = []
        if not specs:
            problems.append("no documents to get")
        for i, spec in enumerate(specs or []):
            if "_id" not in spec:
                problems.append("id is missing")
            if spec.get("_index", default_index) is None:
                problems.append("index is missing")
        if problems:
            raise ActionRequestValidationException(
                "Validation Failed: " + " ".join(
                    f"{i + 1}: {p};" for i, p in enumerate(problems)))
        docs = []
        for spec in specs:
            index = spec.get("_index", default_index)
            routing = spec.get("routing", spec.get("_routing"))
            if routing is None:
                # legacy _parent: the parent id routes the doc
                routing = spec.get("parent", spec.get("_parent"))
            if routing is not None:
                routing = str(routing)
            try:
                d = self.get_doc(index, str(spec["_id"]), routing,
                                 realtime=realtime, refresh=refresh)
                try:
                    svc = self.index_service(index)
                except Exception:  # noqa: BLE001 — handled as missing
                    svc = None
                stored = (spec.get("stored_fields") or spec.get("fields")
                          or stored_fields)
                if isinstance(stored, str):
                    # MultiGetRequest accepts a single field name / CSV
                    stored = [f for f in stored.split(",") if f]
                if d.get("found") and stored and svc is not None:
                    if "_parent" in stored:
                        p = svc.parents.get(str(spec["_id"]))
                        if p is not None:
                            d["_parent"] = p
                    src = d.get("_source") or {}
                    fields = {}
                    for f in stored:
                        if f in ("_source", "_parent", "_routing"):
                            continue
                        ft = svc.mapper_service.field_type(f)
                        if (ft is None or not ft.params.get("store", False)
                                or f not in src):
                            continue
                        v = src[f]
                        fields[f] = v if isinstance(v, list) else [v]
                    if fields:
                        d["fields"] = fields
                    if "_source" not in stored:
                        d.pop("_source", None)
                if d.get("found") and "_source" in spec:
                    # per-doc source filtering (FetchSourceContext)
                    from elasticsearch_tpu.search.service import (
                        _parse_source_spec,
                        filter_source,
                    )

                    inc, exc, enabled = _parse_source_spec(spec["_source"])
                    if not enabled:
                        d.pop("_source", None)
                    elif "_source" in d:
                        d["_source"] = filter_source(d["_source"], inc, exc)
                want_type = spec.get("_type", default_type)
                d["_type"] = want_type or "_doc"
                if want_type not in (None, "_all", "_doc"):
                    # a typed request only matches the index's actual type
                    # (alias-aware resolution, like get_doc itself)
                    actual = getattr(svc, "doc_type", "_doc") or "_doc"
                    if want_type != actual:
                        d = {"_index": index, "_type": want_type,
                             "_id": str(spec["_id"]), "found": False}
                docs.append(d)
            except IndexNotFoundException:
                docs.append({
                    "_index": index, "_id": str(spec["_id"]),
                    "_type": spec.get("_type", default_type) or "_doc",
                    "error": {"type": "index_not_found_exception",
                              "reason": f"no such index [{index}]"},
                })
        return {"docs": docs}

    # ------------------------------------------------------------------
    # Bulk (action/bulk/TransportBulkAction: group by shard, per-item results)
    # ------------------------------------------------------------------

    def bulk(self, operations: List[tuple], refresh=None,
             pipeline: Optional[str] = None) -> dict:
        """operations: list of (action, meta, source_or_None)."""
        t0 = time.monotonic()
        items = []
        errors = False
        touched = set()
        for action, meta, source in operations:
            index = meta.get("_index")
            doc_id = meta.get("_id")
            routing = meta.get("routing") or meta.get("_routing")
            parent = meta.get("parent") or meta.get("_parent")
            if routing is None and parent is not None:
                # legacy _parent: the parent id routes the doc
                routing = str(parent)
            item_pipeline = meta.get("pipeline", pipeline)
            try:
                if action == "index":
                    r = self.index_doc(index, doc_id, source, routing,
                                       pipeline=item_pipeline,
                                       parent=(str(parent)
                                               if parent is not None else None))
                    status = 201 if r.get("result") == "created" else 200
                elif action == "create":
                    r = self.index_doc(index, doc_id, source, routing,
                                       op_type="create", pipeline=item_pipeline,
                                       parent=(str(parent)
                                               if parent is not None else None))
                    status = 201
                elif action == "update":
                    r = self.update_doc(index, doc_id, source, routing)
                    status = 200
                elif action == "delete":
                    r = self.delete_doc(index, doc_id, routing)
                    status = 200 if r.get("found") else 404
                else:
                    raise ActionRequestValidationException(
                        f"Malformed action/metadata line, expected one of "
                        f"[create, delete, index, update] but found [{action}]"
                    )
                if (parent is not None and r.get("_id")
                        and action in ("index", "create", "update")):
                    svc_p = self.indices.get(index)
                    if svc_p is not None:
                        svc_p.parents[str(r["_id"])] = str(parent)
                touched.add(r.get("_index", index))
                item = {action: {**{k: v for k, v in r.items() if k != "found"},
                                 "status": status}}
            except Exception as e:  # per-item failure (reference behavior)
                errors = True
                from elasticsearch_tpu.common.errors import ElasticsearchTpuException

                if isinstance(e, ElasticsearchTpuException):
                    err = e.to_dict()["error"]
                    status = e.status_code
                else:
                    err = {"type": type(e).__name__, "reason": str(e)}
                    status = 500
                item = {action: {
                    "_index": index, "_id": doc_id, "status": status, "error": err,
                }}
            items.append(item)
        if refresh in (True, "true", "", "wait_for"):
            for name in touched:
                if name in self.indices:
                    self.indices[name].refresh()
        return {
            "took": int((time.monotonic() - t0) * 1000),
            "errors": errors,
            "items": items,
        }

    # ------------------------------------------------------------------
    # Search (+ msearch, scroll)
    # ------------------------------------------------------------------

    def search(self, expression: str, body: Optional[dict] = None,
               scroll: Optional[str] = None) -> dict:
        pairs, clusters = self._resolve_search_groups(expression or "_all")
        body = body or {}
        body = self._rewrite_indexed_shapes(body)
        if scroll and body.get("collapse"):
            raise IllegalArgumentException(
                "cannot use `collapse` in a scroll context")
        if scroll and int(body.get("from", 0) or 0):
            # SearchRequest.validate(): paging within a scroll is the
            # scroll itself; an offset would silently desync the pages
            raise IllegalArgumentException(
                "using [from] is not allowed in a scroll context")
        # point-in-time pin (ScrollContext analog): freeze every local
        # shard's segment set + live masks BEFORE the first page, so all
        # pages (including this one) read the same snapshot. CCS scrolls
        # keep cursor semantics — remote segments can't be pinned.
        pinned = None
        if scroll and clusters is None:
            pinned = self._pin_scroll_segments(pairs)
        # deadline + degradation policy: the request's `timeout` (or
        # search.default_search_timeout) bounds the query phase; the
        # registered task's cancellation trips the same checkpoints
        # (_tasks/_cancel). allow_partial_search_results defaults from
        # search.default_allow_partial_results.
        from elasticsearch_tpu.common.settings import (
            SEARCH_ALLOW_PARTIAL_RESULTS,
        )
        from elasticsearch_tpu.search.cancellation import (
            SearchDeadline,
            parse_search_timeout,
        )

        if "allow_partial_search_results" not in body:
            if not SEARCH_ALLOW_PARTIAL_RESULTS.get(self.settings):
                body = dict(body)
                body["allow_partial_search_results"] = False
        task = self.tasks.register("indices:data/read/search", f"search [{expression}]")
        deadline = SearchDeadline(parse_search_timeout(body, self.settings),
                                  task)
        try:
            if len(pairs) == 1 and pairs[0][0] == "" and clusters is None:
                resp = pairs[0][1].search(
                    body, pinned_segments=(pinned or {}).get(
                        pairs[0][1].name) if pinned else None,
                    deadline=deadline)
            else:
                resp = self._multi_index_search(pairs, body, pinned=pinned,
                                                deadline=deadline)
                if clusters is not None:
                    resp["_clusters"] = clusters
        finally:
            self.tasks.unregister(task)
        if scroll:
            if pinned is not None:
                resp["_scroll_id"] = self._open_pit_scroll(
                    pairs, body, resp, scroll, pinned)
            else:
                resp["_scroll_id"] = self._open_scroll(expression, body,
                                                       resp, scroll)
        return resp

    @staticmethod
    def _pin_scroll_segments(pairs) -> Dict[str, Dict[int, list]]:
        from elasticsearch_tpu.index.segment import PinnedSegmentView

        pinned: Dict[str, Dict[int, list]] = {}
        for _prefix, svc in pairs:
            per_shard: Dict[int, list] = {}
            for sid in sorted(svc.shards):
                per_shard[sid] = [
                    PinnedSegmentView(s)
                    for s in svc.shards[sid].engine.searchable_segments()
                ]
            pinned[svc.name] = per_shard
        return pinned

    def _resolve_search_groups(self, expression: str):
        """Split ``alias:index`` cross-cluster groups (TransportSearchAction
        resolving remote indices via RemoteClusterService, reference
        action/search/TransportSearchAction.java:177). Returns
        ([(display_prefix, IndexService)], _clusters dict or None)."""
        from elasticsearch_tpu.common.errors import NodeNotConnectedException

        groups = self.remote_clusters.group_indices(expression)
        pairs = []
        n_remote = sum(1 for alias, _ in groups if alias is not None)
        if n_remote == 0:
            return [("", svc) for svc in
                    self.resolve_search_indices(expression)], None
        skipped = 0
        has_local = False
        for alias, expr in groups:
            if alias is None:
                has_local = True
                pairs.extend(("", svc)
                             for svc in self.resolve_search_indices(expr))
                continue
            rnode, skip_unavailable = self.remote_clusters.get_remote(alias)
            if rnode is None:
                if skip_unavailable:
                    skipped += 1
                    continue
                raise NodeNotConnectedException(
                    f"unable to connect to remote cluster [{alias}]")
            try:
                pairs.extend((f"{alias}:", svc)
                             for svc in rnode.resolve_search_indices(expr))
            except IndexNotFoundException:
                if skip_unavailable:
                    skipped += 1
                    continue
                raise
        total = n_remote + (1 if has_local else 0)
        return pairs, {"total": total, "successful": total - skipped,
                       "skipped": skipped}

    def _rewrite_indexed_shapes(self, body: dict) -> dict:
        """Coordinator rewrite (GeoShapeQueryBuilder's Rewriteable): fetch
        each geo_shape query's ``indexed_shape`` reference document and
        inline its shape before shard execution."""
        import json as _json

        if "indexed_shape" not in _json.dumps(body.get("query") or {}):
            return body
        import copy as _copy

        from elasticsearch_tpu.common.errors import ResourceNotFoundException

        body = _copy.deepcopy(body)

        def walk(obj):
            if isinstance(obj, dict):
                gs = obj.get("geo_shape")
                if isinstance(gs, dict):
                    for fname, spec in gs.items():
                        if isinstance(spec, dict) and "indexed_shape" in spec:
                            ref = spec.pop("indexed_shape")
                            if not isinstance(ref, dict) or "index" not in ref \
                                    or "id" not in ref:
                                raise IllegalArgumentException(
                                    "[indexed_shape] requires index and id")
                            g = self.get_doc(ref["index"], ref["id"])
                            if not g.get("found"):
                                raise ResourceNotFoundException(
                                    f"indexed document [{ref['index']}/"
                                    f"{ref['id']}] not found")
                            val = g["_source"]
                            path = str(ref.get("path", "shape"))
                            for part in path.split("."):
                                if not isinstance(val, dict) or part not in val:
                                    raise IllegalArgumentException(
                                        f"field [{path}] not found in indexed "
                                        f"document [{ref['index']}/{ref['id']}]")
                                val = val[part]
                            spec["shape"] = val
                for v in obj.values():
                    walk(v)
            elif isinstance(obj, list):
                for v in obj:
                    walk(v)

        walk(body.get("query"))
        return body

    def _multi_index_search(self, pairs: List[tuple], body: dict,
                            pinned=None, deadline=None) -> dict:
        """Cross-index search: fan out, merge like cross-shard merge.
        ``pairs`` are (display_prefix, IndexService) — the prefix carries
        the remote-cluster alias into hit ``_index`` values (CCS).
        ``pinned``: {index_name: {shard_id: [segment views]}} from an
        open scroll context."""
        from elasticsearch_tpu.common.errors import (
            SearchPhaseExecutionException,
            TaskCancelledException,
        )
        from elasticsearch_tpu.search.aggregations import parse_aggs, run_aggregations
        from elasticsearch_tpu.search.cancellation import (
            TimeExceededException,
        )
        from elasticsearch_tpu.search.service import (
            allow_partial_results,
            fetch_hits,
            merge_refs,
            normalize_sort,
            shard_failure_entry,
        )

        t0 = time.monotonic()
        from_ = int(body.get("from", 0) or 0)
        size = int(body.get("size")) if body.get("size") is not None else 10
        k = from_ + size
        sort_spec = normalize_sort(body.get("sort"))
        from elasticsearch_tpu.search.service import validate_collapse

        collapse_body = body.get("collapse") or {}
        collapse_field = validate_collapse(body)
        all_refs = []
        total = 0
        max_score = None
        views = []
        n_shards = 0
        n_ok = 0
        failures = []
        timed_out = False
        for prefix, svc in pairs:
            display = f"{prefix}{svc.name}"
            svc_pins = (pinned or {}).get(svc.name)
            for sid in sorted(svc.shards):
                n_shards += 1
                if timed_out or (deadline is not None and deadline.expired):
                    # accumulated shard results stand; remaining shards
                    # are skipped under the expired deadline
                    timed_out = True
                    if deadline is not None:
                        deadline.timed_out = True
                    continue
                try:
                    res = svc.shards[sid].searcher.query(
                        body, size_hint=max(k, 1),
                        segments=(svc_pins.get(sid, [])
                                  if svc_pins is not None else None),
                        deadline=deadline)
                except TaskCancelledException:
                    raise
                except TimeExceededException:
                    timed_out = True
                    continue
                except Exception as e:  # noqa: BLE001 — per-shard isolation
                    from elasticsearch_tpu.index.index_service import (
                        _is_request_error,
                    )

                    if _is_request_error(e):
                        raise  # 4xx validation: keeps its own status
                    failures.append(shard_failure_entry(display, sid, e))
                    continue
                n_ok += 1
                timed_out = timed_out or res.timed_out
                total += res.total_hits
                if res.max_score is not None:
                    max_score = (res.max_score if max_score is None
                                 else max(max_score, res.max_score))
                for ref in res.refs:
                    ref.shard_id = (display, ref.shard_id)
                    all_refs.append(ref)
                views.extend(res.agg_views)
        if failures and n_ok == 0 and not timed_out:
            raise SearchPhaseExecutionException(
                "query", "all shards failed", failures)
        if not allow_partial_results(body) and (failures or timed_out):
            raise SearchPhaseExecutionException(
                "query",
                "Partial shards failure"
                + (" (request timed out)" if timed_out else ""),
                failures)
        shard_map = {}
        for prefix, svc in pairs:
            for sid, shard in svc.shards.items():
                shard_map[(f"{prefix}{svc.name}", sid)] = shard
        if collapse_field:
            from elasticsearch_tpu.search.service import collapse_refs

            refs = merge_refs(all_refs, sort_spec, len(all_refs))
            refs = collapse_refs(refs, collapse_field, shard_map)
            refs = refs[from_: from_ + size]
        else:
            refs = merge_refs(all_refs, sort_spec, max(k, 0))[from_: from_ + size]
        hits = []
        by_index: Dict[str, List] = {}
        for ref in refs:
            by_index.setdefault(ref.shard_id[0], []).append(ref)
        ordered_hits = {}
        for idx_name, idx_refs in by_index.items():
            sub_shards = {r.shard_id: shard_map[r.shard_id] for r in idx_refs}
            # refs carry (display, sid) composite ids here; re-key the
            # pinned views the same way for the fetch-phase lookup
            sub_pins = None
            if pinned is not None and idx_name in pinned:
                sub_pins = {(idx_name, sid): views
                            for sid, views in pinned[idx_name].items()}
            for ref, hit in zip(idx_refs,
                                fetch_hits(idx_refs, sub_shards, body,
                                           idx_name,
                                           pinned_segments=sub_pins)):
                ordered_hits[id(ref)] = hit
        hits = [ordered_hits[id(r)] for r in refs if id(r) in ordered_hits]
        if collapse_field:
            from elasticsearch_tpu.search.service import expand_collapsed_hits

            # ExpandSearchPhase across all clusters/indices of the request
            expand_collapsed_hits(
                hits, refs, collapse_body, body,
                lambda sub: self._multi_index_search(pairs, sub,
                                                     deadline=deadline))
        resp = {
            "took": int((time.monotonic() - t0) * 1000),
            "timed_out": timed_out,
            "_shards": {"total": n_shards,
                        "successful": n_shards - len(failures),
                        "skipped": 0,
                        "failed": len(failures)},
            "hits": {"total": total, "max_score": max_score, "hits": hits},
        }
        if failures:
            resp["_shards"]["failures"] = failures
        agg_specs = parse_aggs(body.get("aggs") or body.get("aggregations"))
        if agg_specs:
            resp["aggregations"] = run_aggregations(agg_specs, views)
        return resp

    def msearch(self, searches: List[tuple]) -> dict:
        """searches: list of (header, body)."""
        responses = []
        for header, body in searches:
            try:
                responses.append(self.search(header.get("index", "_all"), body))
            except Exception as e:
                from elasticsearch_tpu.common.errors import ElasticsearchTpuException

                if isinstance(e, ElasticsearchTpuException):
                    responses.append(e.to_dict())
                else:
                    responses.append({"error": {"type": type(e).__name__,
                                                "reason": str(e)}, "status": 500})
        return {"responses": responses}

    # --- scroll: POINT-IN-TIME search context (search/internal/
    # ScrollContext, SearchService.java:874). Each local shard's segment
    # set + live masks are pinned (PinnedSegmentView) when the scroll
    # opens; every page pages through that frozen snapshot with a stored
    # search_after cursor, so concurrent writes/deletes/refreshes/merges
    # never skip or duplicate docs. Keep-alive expiry and clear_scroll
    # drop the views, releasing the pinned arrays. ---

    def _reap_expired_scrolls(self) -> int:
        now = time.time()
        freed = 0
        with self._scroll_lock:
            for sid, ctx in list(self.scrolls.items()):
                if ctx["expire_at"] < now:
                    del self.scrolls[sid]
                    freed += 1
        return freed

    def _reap_expired_scrolls_loop(self, interval: float = 5.0) -> None:
        while not self._reaper_stop.wait(interval):
            self._reap_expired_scrolls()

    def _register_scroll(self, ctx: dict, keep_alive: str) -> str:
        from elasticsearch_tpu.common.units import parse_time_value

        scroll_id = _uuid.uuid4().hex
        ttl = parse_time_value(keep_alive or "5m", "scroll")
        now = time.time()
        ctx["expire_at"] = now + ttl
        with self._scroll_lock:
            # keep-alive reaper: opening a scroll sweeps expired contexts
            # (frees their pinned segment views)
            for sid_, ctx_ in list(self.scrolls.items()):
                if ctx_["expire_at"] < now:
                    del self.scrolls[sid_]
            self.scrolls[scroll_id] = ctx
        return scroll_id

    def _open_pit_scroll(self, pairs, body: dict, first_resp: dict,
                         keep_alive: str, pinned) -> str:
        """Ordered result over the pinned snapshot as a LAZILY EXTENDED
        PREFIX: opening a size=10 scroll over a large index materializes
        only the first pages' worth of DocRefs, not O(corpus). Deeper
        pages re-query the pinned views with a geometrically growing
        top-k and append only refs not already in the prefix (identity =
        (index, shard, segment, local doc)), so page boundaries never
        skip or duplicate — across ties too, because the served prefix is
        authoritative and the pinned snapshot is immutable. (A plain
        search_after cursor cannot page ties or the sortless relevance
        order safely; the prefix scheme can.)"""
        size = int(body.get("size")) if body.get("size") is not None else 10
        size = max(size, 0)
        # aggregations were already computed by the first-page search;
        # the materialization pass only needs the ordered doc refs
        q_body = {k: v for k, v in body.items()
                  if k not in ("aggs", "aggregations")}
        nd_total = 0
        sources = []
        for prefix, svc in pairs:
            sources.append((prefix, svc.name))
            pins = pinned.get(svc.name) or {}
            for views in pins.values():
                nd_total += sum(v.live_doc_count for v in views)
        ctx = {
            "mode": "pit",
            "entries": [],        # materialized ordered prefix
            "seen": set(),        # identity keys of materialized refs
            "sources": sources,
            "nd_total": nd_total,
            "last_target": 0,
            "exhausted": nd_total == 0,
            "lock": threading.Lock(),  # serializes extension + paging
            "pos": size,
            "body": dict(body),
            "q_body": q_body,
            "pinned": pinned,
            "total": first_resp["hits"]["total"],
            "max_score": first_resp["hits"]["max_score"],
        }
        self._extend_pit_entries(ctx, size)
        # the first page comes from the SAME materialized order, so page
        # boundaries can never skip or duplicate across ties
        first_resp["hits"]["hits"] = self._fetch_scroll_page(
            ctx["entries"][:size], body, pinned)
        return self._register_scroll(ctx, keep_alive)

    def _extend_pit_entries(self, ctx: dict, upto: int) -> None:
        """Grow the materialized prefix to cover [0, upto). Each round
        re-queries every pinned shard with a geometrically larger top-k
        and appends unseen refs in merged order; geometric growth keeps
        total re-query work O(final depth), and a fully drained target
        (target >= pinned live docs, or fewer refs returned than asked)
        marks the context exhausted."""
        from elasticsearch_tpu.search.service import merge_refs, normalize_sort

        sort_spec = normalize_sort(ctx["q_body"].get("sort"))
        while len(ctx["entries"]) < upto and not ctx["exhausted"]:
            target = min(ctx["nd_total"],
                         max(upto, 2 * ctx["last_target"], 32))
            per_ref = []
            for prefix, name in ctx["sources"]:
                svc = self.indices.get(name)
                if svc is None:
                    continue  # index deleted mid-scroll: its docs drop
                pins = ctx["pinned"].get(name) or {}
                for sid in sorted(svc.shards):
                    views = pins.get(sid, [])
                    nd = sum(v.live_doc_count for v in views)
                    if nd == 0:
                        continue
                    res = svc.shards[sid].searcher.query(
                        dict(ctx["q_body"]), size_hint=min(target, nd),
                        segments=views)
                    for ref in res.refs:
                        per_ref.append((prefix, name, ref))
            by_id = {id(r): (p, n) for p, n, r in per_ref}
            merged = merge_refs([r for _, _, r in per_ref], sort_spec,
                                target)
            for r in merged:
                prefix, name = by_id[id(r)]
                key = (prefix, name, r.shard_id, r.segment_name,
                       r.local_doc)
                if key in ctx["seen"]:
                    continue
                ctx["seen"].add(key)
                ctx["entries"].append((prefix, name, r))
            if target >= ctx["nd_total"] or len(merged) < target:
                ctx["exhausted"] = True
            ctx["last_target"] = target

    def _fetch_scroll_page(self, entries, body: dict, pinned) -> List[dict]:
        from elasticsearch_tpu.search.service import fetch_hits

        by_index: Dict[tuple, list] = {}
        for prefix, name, ref in entries:
            by_index.setdefault((prefix, name), []).append(ref)
        ordered = {}
        for (prefix, name), refs in by_index.items():
            svc = self.indices.get(name)
            if svc is None:
                continue  # index deleted mid-scroll: its pinned docs drop
            hits = fetch_hits(refs, svc.shards, body, f"{prefix}{name}",
                              pinned_segments=pinned.get(name))
            for ref, hit in zip(refs, hits):
                ordered[id(ref)] = hit
        return [ordered[id(r)] for _p, _n, r in entries if id(r) in ordered]

    def _open_scroll(self, expression: str, body: dict, first_resp: dict,
                     keep_alive: str) -> str:
        """Cursor-mode scroll (CCS only — remote segments can't be
        pinned): stored search_after state; results can shift with
        remote NRT refreshes, the documented delta vs pinned contexts."""
        body = dict(body)
        if "sort" not in body:
            body["sort"] = [{"_doc": "asc"}]
        return self._register_scroll({
            "mode": "cursor",
            "expression": expression,
            "body": body,
            "last_hits": first_resp["hits"]["hits"],
        }, keep_alive)

    def scroll(self, scroll_id: str, keep_alive: Optional[str] = None) -> dict:
        from elasticsearch_tpu.common.units import parse_time_value

        with self._scroll_lock:
            ctx = self.scrolls.get(scroll_id)
            if ctx is None or ctx["expire_at"] < time.time():
                self.scrolls.pop(scroll_id, None)
                raise ResourceNotFoundException(f"No search context found for id [{scroll_id}]")
        if ctx.get("mode") == "pit":
            t0 = time.monotonic()
            size = (int(ctx["body"].get("size"))
                    if ctx["body"].get("size") is not None else 10)
            size = max(size, 0)
            # extend the materialized prefix on demand (outside the
            # global scroll lock: extension re-queries the pinned views;
            # the per-context lock serializes pagers of THIS scroll)
            with ctx["lock"]:
                pos = ctx["pos"]
                self._extend_pit_entries(ctx, pos + size)
                page = ctx["entries"][pos: pos + size]
                ctx["pos"] = pos + len(page)
            with self._scroll_lock:
                if keep_alive:
                    ctx["expire_at"] = (time.time()
                                        + parse_time_value(keep_alive,
                                                           "scroll"))
            hits = self._fetch_scroll_page(page, ctx["body"], ctx["pinned"])
            return {
                "_scroll_id": scroll_id,
                "took": int((time.monotonic() - t0) * 1000),
                "timed_out": False,
                "hits": {"total": ctx["total"],
                         "max_score": ctx["max_score"], "hits": hits},
            }
        # cursor mode (CCS)
        last_hits = ctx["last_hits"]
        if not last_hits:
            resp = {"_scroll_id": scroll_id, "hits": {"total": 0, "hits": []},
                    "timed_out": False, "took": 0}
            return resp
        body = dict(ctx["body"])
        last_sort = last_hits[-1].get("sort")
        if last_sort is None:
            # relevance-sorted scroll: cursor on score
            body["search_after"] = [last_hits[-1]["_score"]]
        else:
            body["search_after"] = last_sort
        body.pop("from", None)
        resp = self.search(ctx["expression"], body)
        with self._scroll_lock:
            if scroll_id in self.scrolls:
                self.scrolls[scroll_id]["last_hits"] = resp["hits"]["hits"]
                if keep_alive:
                    self.scrolls[scroll_id]["expire_at"] = (
                        time.time() + parse_time_value(keep_alive, "scroll")
                    )
        resp["_scroll_id"] = scroll_id
        return resp

    def clear_scroll(self, scroll_ids: List[str]) -> dict:
        n = 0
        with self._scroll_lock:
            if scroll_ids == ["_all"]:
                n = len(self.scrolls)
                self.scrolls.clear()
            else:
                for sid in scroll_ids:
                    if self.scrolls.pop(sid, None) is not None:
                        n += 1
        return {"succeeded": True, "num_freed": n}

    # ------------------------------------------------------------------
    # Admin / cluster APIs
    # ------------------------------------------------------------------

    def health(self) -> dict:
        return cluster_health(self.cluster_service.state, self.indices)

    def reroute(self, body: Optional[dict] = None, dry_run: bool = False,
                explain: bool = False) -> dict:
        """_cluster/reroute (TransportClusterRerouteAction +
        cluster/routing/allocation/command/): parse the command list,
        apply each against the routing table, then run the allocator to
        normalize (fill unassigned, balance), committing the new table to
        cluster state unless dry_run. Returns the RESULTING state — not a
        blind ack."""
        import copy as _copy

        from elasticsearch_tpu.cluster import allocation as alloc
        from elasticsearch_tpu.common.errors import IllegalArgumentException

        state = self.cluster_service.state
        data_nodes = [nid for nid, n in state.nodes.items()
                      if "data" in n.roles]
        # accepted node addresses: id or name (the reference resolves
        # both through DiscoveryNodes.resolveNode)
        node_ids = {nid: nid for nid in state.nodes}
        node_ids.update({n.name: nid for nid, n in state.nodes.items()})
        open_meta = {name: md for name, md in state.indices.items()}
        table = state.routing
        if table is None:
            table = alloc.allocate(open_meta, data_nodes)
        table = _copy.deepcopy(table)
        explanations = []
        for cmd in (body or {}).get("commands") or []:
            if not isinstance(cmd, dict) or len(cmd) != 1:
                raise IllegalArgumentException(
                    f"malformed reroute command {cmd!r}")
            (name, args), = cmd.items()
            try:
                explanations.append(alloc.apply_command(
                    table, open_meta, node_ids, name, dict(args or {})))
            except alloc.RerouteException as e:
                raise IllegalArgumentException(str(e)) from None
        # normalize: the allocator keeps sticky placements, fills
        # unassigned copies and retires finished relocations
        new_table = alloc.allocate(open_meta, data_nodes, previous=table)
        # single-node reality check: a primary routed to THIS node is
        # backed by a live local shard — report it STARTED (the recovery
        # that would move INITIALIZING->STARTED already happened)
        for shards in new_table.values():
            for copies in shards.values():
                for c in copies:
                    if c.primary and c.node_id == self.node_id:
                        c.state = "STARTED"
        if dry_run:
            preview = state.copy(routing=new_table)
            resp = {"acknowledged": True, "state": preview.to_dict()}
        else:
            new_state = self.cluster_service.submit_state_update_task(
                "cluster_reroute (api)",
                lambda s: s.copy(routing=new_table))
            resp = {"acknowledged": True, "state": new_state.to_dict()}
        if explain:
            resp["explanations"] = explanations
        return resp

    def cluster_stats(self) -> dict:
        state = self.cluster_service.state
        total_docs = sum(svc.num_docs for svc in self.indices.values())
        return {
            "cluster_name": state.cluster_name,
            "status": self.health()["status"],
            "indices": {
                "count": len(self.indices),
                "docs": {"count": total_docs},
                "shards": {
                    "total": sum(s.num_shards for s in self.indices.values()),
                },
            },
            "nodes": {
                "count": {"total": 1, "data": 1, "master": 1, "ingest": 1},
                "versions": [__version__],
            },
        }

    def node_info(self) -> dict:
        return {
            "cluster_name": self.cluster_service.state.cluster_name,
            "nodes": {
                self.node_id: {
                    "name": self.node_name,
                    "version": __version__,
                    "roles": ["master", "data", "ingest"],
                    "settings": self.settings.as_nested_dict(),
                    "plugins": self.plugins_service.info(),
                    "http": {
                        "publish_address": getattr(
                            self, "http_publish_address", None),
                    },
                }
            },
        }

    def node_stats(self) -> dict:
        # node-level search section (ISSUE 8, docs/OBSERVABILITY.md):
        # per-index search blocks — phase histograms, plane/ladder
        # counters, quarantine events, batching — merged into one view
        from elasticsearch_tpu.common.memory import memory_accountant
        from elasticsearch_tpu.search.telemetry import merge_phase_stats
        from elasticsearch_tpu.transport.local import (
            aggregate_transport_stats,
        )

        search = merge_phase_stats(
            [svc.search_stats() for svc in self.indices.values()])
        # the device-memory ledger is a NODE resource: report the
        # node-wide view instead of summed per-index blocks (summing
        # restage_amplification ratios would be meaningless)
        search["memory"] = memory_accountant().stats(None)
        # the compile plane is a process resource too: re-export the
        # node-wide block instead of the per-index sum (ISSUE 14)
        from elasticsearch_tpu.common.compile_cache import compile_stats

        search["compile"] = compile_stats().stats()
        return {
            "cluster_name": self.cluster_service.state.cluster_name,
            "nodes": {
                self.node_id: {
                    "name": self.node_name,
                    "indices": {
                        "docs": {"count": sum(s.num_docs for s in self.indices.values())},
                        "search": search,
                    },
                    "jvm": {"uptime_in_millis": int((time.time() - self.start_time) * 1000)},
                    # monitor probes (OsProbe/ProcessProbe/FsProbe analogs)
                    "os": monitor.os_stats(),
                    "process": monitor.process_stats(),
                    "fs": monitor.fs_stats(
                        self.data_path if self.persistent_path else "."),
                    "thread_pool": self.thread_pool.stats(),
                    "breakers": self.breaker_service.stats(),
                    # PR-2 transport resilience counters (RetryPolicy
                    # retries/backoff waits, send timeouts,
                    # ConnectionHealth fast-fails), aggregated across
                    # every in-process TransportService — they existed
                    # but were never exported (docs/RESILIENCE.md)
                    "transport": aggregate_transport_stats(),
                }
            },
        }

    def put_template(self, name: str, body: dict) -> dict:
        body = dict(body)
        body.setdefault("index_patterns", body.pop("template", None) or [])
        if isinstance(body["index_patterns"], str):
            body["index_patterns"] = [body["index_patterns"]]

        def update(state: ClusterState) -> ClusterState:
            new = state.copy()
            new.templates[name] = body
            return new

        self.cluster_service.submit_state_update_task(f"put-template [{name}]", update)
        return {"acknowledged": True}

    def delete_template(self, name: str) -> dict:
        if name not in self.cluster_service.state.templates:
            raise ResourceNotFoundException(
                f"index_template [{name}] missing"
            )

        def update(state: ClusterState) -> ClusterState:
            new = state.copy()
            new.templates.pop(name, None)
            return new

        self.cluster_service.submit_state_update_task(f"delete-template [{name}]", update)
        return {"acknowledged": True}

    def update_aliases(self, actions: List[dict]) -> dict:
        def update(state: ClusterState) -> ClusterState:
            new = state.copy()
            for action in actions:
                ((verb, spec),) = action.items()
                indices = spec.get("indices") or [spec.get("index")]
                aliases = spec.get("aliases") or [spec.get("alias")]
                for idx_expr in indices:
                    for idx in new.resolve_index_names(idx_expr):
                        for alias in aliases:
                            if verb == "add":
                                meta = {k: spec[k]
                                        for k in ("filter", "routing",
                                                  "index_routing",
                                                  "search_routing")
                                        if k in spec}
                                new.indices[idx].aliases[alias] = meta
                            elif verb == "remove":
                                new.indices[idx].aliases.pop(alias, None)
                            else:
                                raise IllegalArgumentException(
                                    f"[aliases] unknown action [{verb}]"
                                )
            return new

        self.cluster_service.submit_state_update_task("update-aliases", update)
        return {"acknowledged": True}

    def put_cluster_settings(self, body: dict) -> dict:
        persistent = Settings.from_dict(body.get("persistent") or {})
        transient = Settings.from_dict(body.get("transient") or {})

        def update(state: ClusterState) -> ClusterState:
            new = state.copy()
            old_merged = state.persistent_settings.merged_with(state.transient_settings)
            new.persistent_settings = state.persistent_settings.merged_with(persistent)
            new.transient_settings = state.transient_settings.merged_with(transient)
            merged = new.persistent_settings.merged_with(new.transient_settings)
            self.cluster_settings.apply_settings(old_merged, merged)
            return new

        self.cluster_service.submit_state_update_task("update-settings", update)
        state = self.cluster_service.state
        # dynamic remote-cluster registration (search.remote.<alias>.seeds)
        self.remote_clusters.apply_settings(
            state.persistent_settings.merged_with(state.transient_settings))
        # block-max pruning overrides (docs/PRUNING.md): win over each
        # index's creation-time Settings while EXPLICITLY set in the
        # cluster settings, and clear back to None (index settings win
        # again) when absent — synced here from the committed state
        # because the value-only update consumers can't see explicitness
        from elasticsearch_tpu.common.settings import (
            SEARCH_AGGS_FUSED,
            SEARCH_KNN_ENABLED,
            SEARCH_KNN_TILE_SUB,
            SEARCH_PALLAS_PRUNING_ENABLED,
            SEARCH_PALLAS_PRUNING_PROBE_TILES,
            SEARCH_TELEMETRY_ENABLED,
        )

        committed = state.persistent_settings.merged_with(
            state.transient_settings)
        for setting, attr in (
                (SEARCH_PALLAS_PRUNING_ENABLED,
                 "pruning_enabled_override"),
                (SEARCH_PALLAS_PRUNING_PROBE_TILES,
                 "pruning_probe_override"),
                # kNN plane knobs share the explicitness contract: the
                # cluster-level value wins while set, and clearing it
                # hands control back to the index's own Settings
                (SEARCH_KNN_ENABLED, "knn_enabled_override"),
                (SEARCH_KNN_TILE_SUB, "knn_tile_sub_override"),
                # fused on-device aggregations (ISSUE 13, docs/AGGS.md):
                # same explicitness contract — the cluster value wins
                # while set, clearing reverts to index/node settings
                (SEARCH_AGGS_FUSED, "aggs_fused_override"),
                # telemetry kill switch follows the same explicitness
                # contract (docs/OBSERVABILITY.md)
                (SEARCH_TELEMETRY_ENABLED, "telemetry_enabled_override")):
            explicit = committed.get(setting.key) is not None
            value = setting.get(committed) if explicit else None
            for svc in self.indices.values():
                setattr(svc, attr, value)
        # overload-control knobs (search.queue.* / search.admission.* /
        # search.batch.max_window_ms — ISSUE 12, docs/OVERLOAD.md) share
        # the explicitness contract: each live admission controller
        # installs the committed cluster settings' EXPLICIT keys as
        # overrides; a cleared key hands control back to the index's own
        # Settings map. (The controller reads its config live, so no
        # value-only update consumers are needed.)
        for svc in self.indices.values():
            svc.admission.set_cluster_overrides(committed)
        # the REST search pool's queue moves with the same key (the
        # "both backpressure points" contract, docs/OVERLOAD.md):
        # explicit cluster value wins, clearing reverts to the node file
        qsize_key = "search.queue.size"
        qsize_src = (committed if committed.get(qsize_key) is not None
                     else self.settings)
        self.thread_pool.executor("search").resize_queue(
            qsize_src.get_int(qsize_key, 1000))
        # HBM budget (search.memory.hbm_budget_bytes): the accountant is
        # a process resource — an explicit cluster-level value wins, and
        # clearing it reverts to the node-file setting; lowering the
        # budget LRU-evicts immediately (set_budget → enforce_budget)
        from elasticsearch_tpu.common.memory import memory_accountant

        budget_key = "search.memory.hbm_budget_bytes"
        if committed.get(budget_key) is not None:
            memory_accountant().set_budget(
                committed.get_bytes(budget_key, 0))
        else:
            memory_accountant().set_budget(
                self.settings.get_bytes(budget_key, 0))
        # device-staging retry knobs (search.staging.retry.*): explicit
        # cluster values win; clearing them reverts to the node file
        # (the value-only update consumers can't see explicitness)
        from elasticsearch_tpu.common.settings import (
            SEARCH_STAGING_RETRY_BACKOFF_MS,
            SEARCH_STAGING_RETRY_MAX_ATTEMPTS,
        )

        from elasticsearch_tpu.common.staging import configure_staging_retry

        for setting, kw in (
                (SEARCH_STAGING_RETRY_MAX_ATTEMPTS, "max_attempts"),
                (SEARCH_STAGING_RETRY_BACKOFF_MS, "backoff_ms")):
            source = (committed if committed.get(setting.key) is not None
                      else self.settings)
            configure_staging_retry(**{kw: setting.get(source)})
        # background integrity scrubber cadence (index.scrub.interval,
        # ISSUE 16, docs/RESILIENCE.md "Data integrity"): same
        # explicitness contract — an explicit cluster value overrides
        # every index's own setting, clearing hands control back
        from elasticsearch_tpu.common.settings import INDEX_SCRUB_INTERVAL

        scrub_explicit = committed.get(INDEX_SCRUB_INTERVAL.key) is not None
        scrub_value = (INDEX_SCRUB_INTERVAL.get(committed)
                       if scrub_explicit else None)
        for svc in self.indices.values():
            svc.scrub_interval_override = scrub_value
        # delta device staging knobs (index.staging.*, ISSUE 20): same
        # explicitness contract — an explicit cluster value overrides
        # every index's own setting, clearing hands control back
        from elasticsearch_tpu.common.settings import (
            INDEX_STAGING_COMPACT_THRESHOLD,
            INDEX_STAGING_DELTA_ENABLED,
        )

        delta_explicit = (
            committed.get(INDEX_STAGING_DELTA_ENABLED.key) is not None)
        delta_value = (INDEX_STAGING_DELTA_ENABLED.get(committed)
                       if delta_explicit else None)
        compact_explicit = (
            committed.get(INDEX_STAGING_COMPACT_THRESHOLD.key) is not None)
        compact_value = (INDEX_STAGING_COMPACT_THRESHOLD.get(committed)
                         if compact_explicit else None)
        for svc in self.indices.values():
            svc.staging_delta_enabled_override = delta_value
            svc.staging_compact_threshold_override = compact_value
        return {
            "acknowledged": True,
            "persistent": state.persistent_settings.as_nested_dict(),
            "transient": state.transient_settings.as_nested_dict(),
        }

    def update_index_settings(self, expression: str, body: dict) -> dict:
        normalized = Settings.from_dict(
            body.get("settings", body) or {}).with_index_prefix()
        self.index_scoped_settings.validate_dynamic_update(normalized)
        names = self.cluster_service.state.resolve_index_names(expression)

        def update(state: ClusterState) -> ClusterState:
            new = state.copy()
            for n in names:
                md = new.indices[n]
                md.settings = md.settings.merged_with(normalized)
                md.version += 1
            return new

        self.cluster_service.submit_state_update_task("update-index-settings", update)
        for n in names:
            svc = self.indices[n]
            svc.settings = svc.settings.merged_with(normalized)
            # dynamic knobs consumed at query time re-read per request
            # through svc.settings; per-searcher cached ones re-sync here
            for shard in svc.shards.values():
                shard.searcher.max_slices = svc.settings.get_int(
                    "index.max_slices_per_scroll", 1024)
            self._persist_index_meta(n)
        return {"acknowledged": True}

    def termvectors(self, index: str, doc_id: str,
                    fields: Optional[List[str]] = None) -> dict:
        """_termvectors (action/termvectors/TransportTermVectorsAction):
        per-field terms with freq + positions for one doc."""
        svc = self.index_service(index)
        shard = svc.shards[svc._route(doc_id)]
        shard.refresh()
        term_vectors: Dict[str, dict] = {}
        found = False
        for seg in shard.engine.searchable_segments():
            local = seg.id_to_doc().get(doc_id)
            if local is None or not seg.live[local]:
                continue
            found = True
            by_field: Dict[str, dict] = {}
            for tid, per_doc in seg.positions.items():
                if local not in per_doc:
                    continue
                key = seg.term_keys[tid]
                fname, token = key.split("\x1f", 1)
                if fields and fname not in fields:
                    continue
                f = by_field.setdefault(fname, {"terms": {}})
                f["terms"][token] = {
                    "term_freq": int(len(per_doc[local])),
                    "doc_freq": int(seg.term_doc_freq[tid]),
                    "tokens": [{"position": int(p)} for p in per_doc[local]],
                }
            for fname, f in by_field.items():
                st = seg.field_stats.get(fname, {})
                f["field_statistics"] = {
                    "sum_ttf": st.get("sum_ttf", 0),
                    "doc_count": st.get("doc_count", 0),
                }
                term_vectors[fname] = f
            break
        return {
            "_index": svc.name,
            "_id": doc_id,
            "found": found,
            "term_vectors": term_vectors,
        }

    def rollover(self, alias: str, body: Optional[dict] = None) -> dict:
        """_rollover (action/admin/indices/rollover): when conditions are
        met, create the next index in the series and move the write alias."""
        body = body or {}
        state = self.cluster_service.state
        sources = [n for n, md in state.indices.items() if alias in md.aliases]
        if len(sources) != 1:
            raise IllegalArgumentException(
                f"source alias [{alias}] must point to exactly one index, "
                f"found {sources}"
            )
        source = sources[0]
        import re as _re

        m = _re.search(r"-(\d+)$", source)
        if body.get("new_index"):
            target = body["new_index"]
        elif m:
            n = int(m.group(1)) + 1
            target = f"{source[:m.start()]}-{n:06d}"
        else:
            target = f"{source}-000002"
        svc = self.indices[source]
        conditions = body.get("conditions") or {}
        results = {}
        met = not conditions
        from elasticsearch_tpu.common.units import parse_byte_size, parse_time_value

        if "max_docs" in conditions:
            ok = svc.num_docs >= int(conditions["max_docs"])
            results["[max_docs: {}]".format(conditions["max_docs"])] = ok
            met = met or ok
        if "max_age" in conditions:
            age = time.time() - svc.creation_date / 1000.0
            ok = age >= parse_time_value(conditions["max_age"], "max_age")
            results["[max_age: {}]".format(conditions["max_age"])] = ok
            met = met or ok
        if "max_size" in conditions:
            size = sum(s.stats()["segments"]["memory_in_bytes"]
                       for s in svc.shards.values())
            ok = size >= parse_byte_size(conditions["max_size"], "max_size")
            results["[max_size: {}]".format(conditions["max_size"])] = ok
            met = met or ok
        resp = {
            "old_index": source,
            "new_index": target,
            "rolled_over": False,
            "dry_run": bool(body.get("dry_run", False)),
            "conditions": results,
            "acknowledged": False,
            "shards_acknowledged": False,
        }
        if not met or body.get("dry_run"):
            return resp
        create_body = {k: v for k, v in body.items()
                       if k in ("settings", "mappings", "aliases")}
        self.create_index(target, create_body)
        self.update_aliases([
            {"remove": {"index": source, "alias": alias}},
            {"add": {"index": target, "alias": alias}},
        ])
        resp.update({"rolled_over": True, "acknowledged": True,
                     "shards_acknowledged": True})
        return resp

    def shrink_index(self, source: str, target: str,
                     body: Optional[dict] = None) -> dict:
        """_shrink (action/admin/indices/shrink): re-partition into fewer
        shards. The reference hard-links segment files; we re-route docs
        (offline repartition, same semantics: SURVEY.md §5.7)."""
        body = body or {}
        svc = self.index_service(source)
        settings = dict((body.get("settings") or {}))
        target_shards = int(
            Settings.from_dict(settings).with_index_prefix()
            .get("index.number_of_shards", 1)
        )
        # pin the validated count into the create body: the index-level
        # DEFAULT is 5 (6.x), so an unset value must not silently build
        # an unshrunk 5-shard target
        settings.setdefault("index.number_of_shards", target_shards)
        if svc.num_shards % target_shards != 0:
            raise IllegalArgumentException(
                f"the number of source shards [{svc.num_shards}] must be a "
                f"multiple of [{target_shards}]"
            )
        svc.refresh()
        self.create_index(target, {
            "settings": settings,
            "mappings": svc.mapping_dict(),
            "aliases": body.get("aliases") or {},
        })
        tgt = self.indices[target]
        for shard in svc.shards.values():
            for seg in shard.engine.searchable_segments():
                for local in range(seg.num_docs):
                    if seg.live[local]:
                        tgt.index_doc(seg.doc_ids[local], seg.sources[local],
                                      seg.routings[local])
        tgt.refresh()
        return {"acknowledged": True, "shards_acknowledged": True, "index": target}

    HOT_THREADS_INTERVAL_S = 0.05

    @staticmethod
    def _thread_cpu_seconds() -> dict:
        """Per-thread CPU time (user+system seconds) via the kernel's
        per-task accounting: python thread -> its native tid ->
        /proc/self/task/<tid>/stat fields 14/15. Returns {} on platforms
        without procfs (the dump then reports stacks without CPU%)."""
        import os
        import threading

        out = {}
        try:
            tick = os.sysconf("SC_CLK_TCK")
        except (ValueError, OSError, AttributeError):
            return out
        for th in threading.enumerate():
            tid = getattr(th, "native_id", None)
            if tid is None:
                continue
            try:
                with open(f"/proc/self/task/{tid}/stat", "rb") as f:
                    # comm can contain spaces/parens: split AFTER the
                    # closing paren; utime/stime are then fields 11/12
                    parts = f.read().rpartition(b")")[2].split()
                out[th.ident] = (int(parts[11]) + int(parts[12])) / tick
            except (OSError, IndexError, ValueError):
                continue
        return out

    def hot_threads(self) -> str:
        """_nodes/hot_threads (monitor/jvm/HotThreads): REAL per-thread
        CPU sampling + stacks, busiest first. Two CPU-time snapshots
        bracket a short sleep; each live thread reports its measured CPU%
        over the interval, its name, and its current stack — so a waiter
        stuck on _MESH_EXEC_LOCK (or any other contended lock) is
        directly visible with 0% CPU and the acquire frame on top."""
        import sys
        import threading
        import traceback

        interval = self.HOT_THREADS_INTERVAL_S
        cpu0 = self._thread_cpu_seconds()
        time.sleep(interval)
        cpu1 = self._thread_cpu_seconds()
        frames = sys._current_frames()
        rows = []
        known = set()
        for th in threading.enumerate():
            cpu = max(cpu1.get(th.ident, 0.0) - cpu0.get(th.ident, 0.0),
                      0.0)
            rows.append((cpu, th.ident, th.name, th.daemon))
            known.add(th.ident)
        # sys._current_frames() also sees threads never registered with
        # the threading module (C-extension/backend callback threads
        # running Python code): report them too, CPU unattributed
        for ident in frames.keys() - known:
            rows.append((0.0, ident, "<non-threading>", False))
        rows.sort(key=lambda r: (-r[0], r[2]))
        out = [
            f"::: {{{self.node_name}}}{{{self.node_id}}}",
            f"   Hot threads sampled over {interval * 1000:.0f}ms, "
            f"{len(rows)} live threads, busiest first:",
        ]
        for cpu, ident, name, daemon in rows:
            pct = cpu / interval * 100.0 if interval else 0.0
            flags = " (daemon)" if daemon else ""
            out.append(
                f"\n   {pct:6.1f}% ({cpu * 1000:.1f}ms out of "
                f"{interval * 1000:.0f}ms) cpu usage by thread id "
                f"[{ident}] '{name}'{flags}:")
            frame = frames.get(ident)
            if frame is None:
                out.append("     <no stack available>")
                continue
            out.extend("     " + line.rstrip("\n") for line in
                       traceback.format_stack(frame, limit=12))
        return "\n".join(out)

    def put_stored_script(self, script_id: str, body: dict) -> dict:
        def update(state: ClusterState) -> ClusterState:
            new = state.copy()
            new.stored_scripts[script_id] = body.get("script", body)
            return new

        self.cluster_service.submit_state_update_task(f"put-script [{script_id}]", update)
        return {"acknowledged": True}

    def get_stored_script(self, script_id: str) -> dict:
        script = self.cluster_service.state.stored_scripts.get(script_id)
        if script is None:
            raise ResourceNotFoundException(f"unable to find script [{script_id}]")
        return {"_id": script_id, "found": True, "script": script}

    def _start_compile_warming(self) -> None:
        """Background AOT warming of every recovered index's recorded
        program-variant lattice (daemon thread — never blocks boot or
        the first query; the query path simply finds warm programs)."""
        from elasticsearch_tpu.common import compile_cache as _cc

        targets = [svc for svc in self.indices.values()
                   if _cc.variant_registry().warm_entries(svc.name)]
        if not targets:
            return

        def warm():
            for svc in targets:
                try:
                    svc.warm_compile_variants()
                except Exception:  # noqa: BLE001 — warming is best-effort
                    pass

        threading.Thread(target=warm, daemon=True,
                         name=f"compile-warm[{self.node_name}]").start()

    # ------------------------------------------------------------------
    # Graceful drain + shutdown (ISSUE 14, docs/RESILIENCE.md
    # "Rollout & drain")
    # ------------------------------------------------------------------

    def _drain_deadline_s(self) -> float:
        committed = self.cluster_service.state.persistent_settings \
            .merged_with(self.cluster_service.state.transient_settings)
        source = (committed if committed.get("search.drain.deadline")
                  is not None else self.settings)
        v = source.get_time("search.drain.deadline", 30.0)
        return float(v) if v is not None else 30.0

    def drain(self, deadline_s: Optional[float] = None) -> dict:
        """Enter the draining state (the rollout API): every index's
        admission controller stops admitting (clean 503 + Retry-After;
        queued entries shed with the same contract), in-flight searches
        finish within the drain deadline, then every shard flushes with
        a synced-flush marker so warm restart recovery is ops-free.
        Idempotent; ``undrain()`` aborts. Returns the drain report."""
        t0 = time.monotonic()
        deadline_s = (self._drain_deadline_s() if deadline_s is None
                      else float(deadline_s))
        self._draining = True
        shed = 0
        for svc in self.indices.values():
            shed += svc.admission.begin_drain()
        deadline_at = time.monotonic() + deadline_s
        drained = True
        for svc in self.indices.values():
            remaining = max(deadline_at - time.monotonic(), 0.0)
            drained = svc.admission.await_drained(remaining) and drained
        # flush + synced-flush marker AFTER the in-flight work finished:
        # the commit then covers every acked op (ops-free warm restart).
        # Only a persistent data path benefits — a tempdir-backed node
        # has nothing to warm-restart into, so skip the commit I/O.
        if self.persistent_path:
            for name in list(self.indices):
                self._persist_index_meta(name)
                try:
                    self.indices[name].synced_flush()
                except Exception:  # noqa: BLE001 — a failed flush must
                    # not block shutdown; translog replay covers the gap
                    pass
        return {
            "draining": True,
            "drained": drained,
            "queued_shed": shed,
            "in_flight_remaining": sum(
                svc.admission.in_flight for svc in self.indices.values()),
            "took_ms": int((time.monotonic() - t0) * 1000),
        }

    def undrain(self) -> dict:
        """Abort a drain (rollout cancelled): indices admit again."""
        self._draining = False
        for svc in self.indices.values():
            svc.admission.end_drain()
        return {"draining": False}

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._reaper_stop.set()
        # shutdown ordering (ISSUE 14): FIRST stop admitting and shed
        # the admission queues (queued entries get the clean rejection
        # contract, not a silent drop), drain in-flight searches within
        # the deadline, and stamp synced-flush markers — all BEFORE the
        # thread pool goes down, so no queued work is stranded behind a
        # dead executor and no index closes under an in-flight search
        self.drain()
        self.thread_pool.shutdown()
        from elasticsearch_tpu.transport.remote_cluster import unregister_node

        unregister_node(self)
        self.plugins_service.close()
        self.snapshots.close()
        for name in list(self.indices):
            self.indices[name].close()


MAPPING_TOP_LEVEL_KEYS = {
    "properties", "dynamic", "dynamic_templates", "_source", "_meta",
    "_routing", "_all", "_field_names", "_size", "_parent",
    "date_detection", "numeric_detection", "dynamic_date_formats",
}


def _unwrap_typed_mapping(mappings):
    """6.x typed mapping form: {"my_type": {...}} wraps the real mapping
    in a single custom type name (deprecated; _doc canonical). Returns
    (mapping, type_name)."""
    if (isinstance(mappings, dict) and len(mappings) == 1):
        (key, inner), = mappings.items()
        if (key not in MAPPING_TOP_LEVEL_KEYS and isinstance(inner, dict)
                and (not inner or set(inner) & MAPPING_TOP_LEVEL_KEYS)):
            return inner, key
    return mappings, "_doc"


def _template_matches(template: dict, index_name: str) -> bool:
    import fnmatch

    patterns = template.get("index_patterns") or []
    if isinstance(patterns, str):
        patterns = [patterns]
    return any(fnmatch.fnmatchcase(index_name, p) for p in patterns)


def _merge_mapping_dicts(base: dict, incoming: dict) -> None:
    for k, v in incoming.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            _merge_mapping_dicts(base[k], v)
        else:
            base[k] = v
