"""Painless-class scripting: lexer, parser, interpreter.

Role model: ``modules/lang-painless`` (Compiler.java:41 — ANTLR grammar,
whitelist-typed AST, JVM bytecode emission). The TPU-native stand-in keeps
the same *surface* — Java-ish statements/expressions, ``doc['f'].value``
doc-value access, ``ctx._source`` update mutation, ``params``, Math/String/
List/Map method whitelists, loop-iteration limits — but executes on a small
tree-walking interpreter: scripts in this engine orchestrate host-side
logic, while the numeric subset keeps compiling through the expression
fast path (script/expression.py) into whole-segment array math.

Deliberately whitelist-only like the reference: unknown methods raise at
runtime, there is no attribute access into interpreter internals, and a
hard statement budget (the analog of painless's LoopCounter) bounds every
execution.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import ParsingException


class ScriptException(ParsingException):
    """Compile or runtime failure — surfaces as a 400 like the
    reference's script_exception."""


# ----------------------------------------------------------------------
# Lexer
# ----------------------------------------------------------------------

_PUNCT = (
    "===", "!==", "==", "!=", "<=", ">=", "&&", "||", "++", "--", "+=",
    "-=", "*=", "/=", "%=", "?:", "?.", "->", "{", "}", "(", ")", "[", "]",
    ";", ",", ".", "+", "-", "*", "/", "%", "<", ">", "=", "!", "?", ":",
)

_KEYWORDS = {
    "if", "else", "while", "for", "return", "break", "continue", "def",
    "in", "new", "true", "false", "null", "int", "long", "double", "float",
    "boolean", "String", "Map", "List", "HashMap", "ArrayList", "Object",
    "void", "instanceof",
}


class Tok:
    __slots__ = ("kind", "text", "pos")

    def __init__(self, kind: str, text: str, pos: int):
        self.kind = kind  # id | num | str | punct | kw | eof
        self.text = text
        self.pos = pos

    def __repr__(self):
        return f"{self.kind}:{self.text!r}"


def _lex(src: str) -> List[Tok]:
    toks: List[Tok] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c in " \t\r\n":
            i += 1
            continue
        if src.startswith("//", i):
            j = src.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if src.startswith("/*", i):
            j = src.find("*/", i + 2)
            if j < 0:
                raise ScriptException("unterminated block comment")
            i = j + 2
            continue
        if c in "'\"":
            j = i + 1
            buf = []
            while j < n and src[j] != c:
                if src[j] == "\\" and j + 1 < n:
                    esc = src[j + 1]
                    buf.append({"n": "\n", "t": "\t", "\\": "\\",
                                "'": "'", '"': '"'}.get(esc, esc))
                    j += 2
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise ScriptException("unterminated string literal")
            toks.append(Tok("str", "".join(buf), i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n:
                ch = src[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    # `1.max(...)` must lex as 1 . max — a dot is part of
                    # the number only when a digit follows
                    if j + 1 < n and src[j + 1].isdigit():
                        seen_dot = True
                        j += 1
                    else:
                        break
                elif ch in "eE" and not seen_exp and j + 1 < n and (
                        src[j + 1].isdigit() or src[j + 1] in "+-"):
                    seen_exp = True
                    j += 2
                else:
                    break
            text = src[i:j]
            if j < n and src[j] in "lLfFdD":  # java literal suffixes
                if src[j] in "fFdD":
                    seen_dot = True
                j += 1
            toks.append(Tok("num", text + ("f" if seen_dot else ""), i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            word = src[i:j]
            toks.append(Tok("kw" if word in _KEYWORDS else "id", word, i))
            i = j
            continue
        for p in _PUNCT:
            if src.startswith(p, i):
                toks.append(Tok("punct", p, i))
                i += len(p)
                break
        else:
            raise ScriptException(f"unexpected character [{c}] at {i}")
    toks.append(Tok("eof", "", n))
    return toks


# ----------------------------------------------------------------------
# Parser -> tuple AST  (kind, ...)
# ----------------------------------------------------------------------

_TYPE_WORDS = {"def", "int", "long", "double", "float", "boolean", "String",
               "Map", "List", "Object", "HashMap", "ArrayList", "void"}


class _Parser:
    def __init__(self, toks: List[Tok]):
        self.toks = toks
        self.i = 0

    def peek(self, k=0) -> Tok:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def op(self, *texts) -> Optional[str]:
        """Current token's text when it's one of the given PUNCT
        operators (a string literal '-' must never match minus)."""
        t = self.toks[self.i]
        if t.kind == "punct" and t.text in texts:
            return t.text
        return None

    def accept(self, text: str) -> bool:
        if self.peek().text == text and self.peek().kind in ("punct", "kw"):
            self.i += 1
            return True
        return False

    def expect(self, text: str) -> None:
        if not self.accept(text):
            raise ScriptException(
                f"expected [{text}] but found [{self.peek().text}]")

    # --- statements ---

    def parse_program(self):
        stmts = []
        while self.peek().kind != "eof":
            stmts.append(self.statement())
        # the trailing expression statement is the script's value
        # (painless source "doc['n'].value * 2" has no explicit return)
        if stmts and stmts[-1][0] == "expr":
            stmts[-1] = ("return", stmts[-1][1])
        return ("block", stmts)

    def block_or_stmt(self):
        if self.accept("{"):
            stmts = []
            while not self.accept("}"):
                stmts.append(self.statement())
            return ("block", stmts)
        return self.statement()

    def statement(self):
        t = self.peek()
        if t.text == "{":
            return self.block_or_stmt()
        if t.text == "if":
            self.next()
            self.expect("(")
            cond = self.expression()
            self.expect(")")
            then = self.block_or_stmt()
            other = None
            if self.accept("else"):
                other = self.block_or_stmt()
            return ("if", cond, then, other)
        if t.text == "while":
            self.next()
            self.expect("(")
            cond = self.expression()
            self.expect(")")
            return ("while", cond, self.block_or_stmt())
        if t.text == "for":
            self.next()
            self.expect("(")
            # for-each: for (def x : expr)
            save = self.i
            if (self.peek().text in _TYPE_WORDS and self.peek(1).kind == "id"
                    and self.peek(2).text == ":"):
                self.next()
                var = self.next().text
                self.expect(":")
                it = self.expression()
                self.expect(")")
                return ("foreach", var, it, self.block_or_stmt())
            self.i = save
            init = None if self.peek().text == ";" else self.simple_statement()
            self.expect(";")
            cond = None if self.peek().text == ";" else self.expression()
            self.expect(";")
            step = None if self.peek().text == ")" else self.expression()
            self.expect(")")
            return ("for", init, cond, step, self.block_or_stmt())
        if t.text == "return":
            self.next()
            val = None
            if self.peek().text != ";" and self.peek().kind != "eof":
                val = self.expression()
            self.accept(";")
            return ("return", val)
        if t.text == "break":
            self.next()
            self.accept(";")
            return ("break",)
        if t.text == "continue":
            self.next()
            self.accept(";")
            return ("continue",)
        s = self.simple_statement()
        self.accept(";")
        return s

    def simple_statement(self):
        # declaration: TYPE name [= expr] (, name [= expr])*
        if (self.peek().text in _TYPE_WORDS and self.peek().text != "void"
                and self.peek(1).kind == "id"):
            self.next()
            decls = []
            while True:
                name = self.next().text
                val = self.expression() if self.accept("=") else None
                decls.append((name, val))
                if not self.accept(","):
                    break
            return ("decl", decls)
        return ("expr", self.expression())

    # --- expressions (precedence climbing) ---

    def expression(self):
        return self.assignment()

    def assignment(self):
        left = self.ternary()
        t = self.op("=", "+=", "-=", "*=", "/=", "%=")
        if t:
            self.next()
            right = self.assignment()
            if left[0] not in ("var", "index", "field"):
                raise ScriptException("invalid assignment target")
            return ("assign", t, left, right)
        return left

    def ternary(self):
        cond = self.elvis()
        if self.accept("?"):
            a = self.assignment()
            self.expect(":")
            b = self.assignment()
            return ("ternary", cond, a, b)
        return cond

    def elvis(self):
        left = self.logic_or()
        if self.accept("?:"):
            return ("elvis", left, self.elvis())
        return left

    def logic_or(self):
        left = self.logic_and()
        while self.accept("||"):
            left = ("or", left, self.logic_and())
        return left

    def logic_and(self):
        left = self.equality()
        while self.accept("&&"):
            left = ("and", left, self.equality())
        return left

    def equality(self):
        left = self.relational()
        while self.op("==", "!=", "===", "!=="):
            op = self.next().text
            left = ("cmp", op[:2], left, self.relational())
        return left

    def relational(self):
        left = self.additive()
        while self.op("<", ">", "<=", ">=") or \
                self.peek().text == "instanceof":
            if self.accept("instanceof"):
                tname = self.next().text
                left = ("instanceof", left, tname)
                continue
            op = self.next().text
            left = ("cmp", op, left, self.additive())
        return left

    def additive(self):
        left = self.multiplicative()
        while self.op("+", "-"):
            op = self.next().text
            left = ("bin", op, left, self.multiplicative())
        return left

    def multiplicative(self):
        left = self.unary()
        while self.op("*", "/", "%"):
            op = self.next().text
            left = ("bin", op, left, self.unary())
        return left

    def unary(self):
        t = self.op("!", "-", "+", "++", "--")
        if t == "!":
            self.next()
            return ("not", self.unary())
        if t == "-":
            self.next()
            return ("neg", self.unary())
        if t == "+":
            self.next()
            return self.unary()
        if t in ("++", "--"):
            self.next()
            target = self.unary()
            return ("assign", "+=" if t == "++" else "-=", target,
                    ("num", 1))
        return self.postfix()

    def postfix(self):
        node = self.primary()
        while True:
            if self.accept("."):
                name = self.next().text
                if self.accept("("):
                    args = self.call_args()
                    node = ("call", node, name, args)
                else:
                    node = ("field", node, name)
            elif self.accept("?."):
                name = self.next().text
                if self.accept("("):
                    args = self.call_args()
                    node = ("safecall", node, name, args)
                else:
                    node = ("safefield", node, name)
            elif self.accept("["):
                idx = self.expression()
                self.expect("]")
                node = ("index", node, idx)
            elif self.op("++", "--") and node[0] in (
                    "var", "index", "field"):
                op = self.next().text
                node = ("postincr", "+=" if op == "++" else "-=", node)
            else:
                return node

    def call_args(self):
        args = []
        if self.accept(")"):
            return args
        while True:
            args.append(self.expression())
            if self.accept(")"):
                return args
            self.expect(",")

    def primary(self):
        t = self.next()
        if t.kind == "num":
            if t.text.endswith("f"):
                return ("num", float(t.text[:-1]))
            return ("num", int(t.text) if "." not in t.text
                    and "e" not in t.text and "E" not in t.text
                    else float(t.text))
        if t.kind == "str":
            return ("str", t.text)
        if t.text == "true":
            return ("bool", True)
        if t.text == "false":
            return ("bool", False)
        if t.text == "null":
            return ("null",)
        if t.text == "new":
            tname = self.next().text
            self.expect("(")
            self.call_args()  # constructor args discarded (sized ctors)
            if tname in ("HashMap", "TreeMap", "LinkedHashMap", "Map"):
                return ("mapinit", [])
            if tname in ("ArrayList", "LinkedList", "List", "HashSet"):
                return ("listinit", [])
            if tname == "StringBuilder":
                return ("strbuilder",)
            raise ScriptException(f"unknown type [new {tname}]")
        if t.text == "(":
            # cast? (int) x — accept and ignore numeric casts
            if (self.peek().text in _TYPE_WORDS
                    and self.peek(1).text == ")"):
                tname = self.next().text
                self.expect(")")
                expr = self.unary()
                return ("cast", tname, expr)
            e = self.expression()
            self.expect(")")
            return e
        if t.text == "[":
            # list initializer [a, b] or map initializer [k: v] / [:]
            if self.accept(":"):
                self.expect("]")
                return ("mapinit", [])
            if self.accept("]"):
                return ("listinit", [])
            first = self.expression()
            if self.accept(":"):
                pairs = [(first, self.expression())]
                while self.accept(","):
                    k = self.expression()
                    self.expect(":")
                    pairs.append((k, self.expression()))
                self.expect("]")
                return ("mapinit", pairs)
            items = [first]
            while self.accept(","):
                items.append(self.expression())
            self.expect("]")
            return ("listinit", items)
        if t.kind in ("id", "kw"):
            return ("var", t.text)
        raise ScriptException(f"unexpected token [{t.text}]")


# ----------------------------------------------------------------------
# Runtime values
# ----------------------------------------------------------------------


class DocValues:
    """doc['field'] — ScriptDocValues semantics: .value is the first
    value (0/'' defaults never apply: missing access raises like the
    reference when the doc has no value), .values/.size()/.empty."""

    __slots__ = ("field", "_values")

    def __init__(self, field: str, values: List[Any]):
        self.field = field
        self._values = values

    @property
    def value(self):
        if not self._values:
            raise ScriptException(
                f"A document doesn't have a value for field [{self.field}]!"
                " Use doc[<field>].size()==0 to check if a document is"
                " missing a field!")
        return self._values[0]

    @property
    def values(self):
        return list(self._values)

    @property
    def empty(self):
        return not self._values

    @property
    def length(self):
        return len(self._values)

    def size(self):
        return len(self._values)


class DocMap:
    """The ``doc`` binding: field name -> DocValues, resolved lazily from
    a segment/local doc or from a prebound {field: [values]} dict."""

    def __init__(self, resolve: Callable[[str], List[Any]]):
        self._resolve = resolve
        self._cache: Dict[str, DocValues] = {}

    def __getitem__(self, field: str) -> DocValues:
        if field not in self._cache:
            self._cache[field] = DocValues(field, self._resolve(field))
        return self._cache[field]

    def containsKey(self, field: str) -> bool:
        return len(self._resolve(field)) > 0


class _StringBuilder:
    __slots__ = ("parts",)

    def __init__(self):
        self.parts: List[str] = []


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class _Return(Exception):
    def __init__(self, value):
        self.value = value


# ----------------------------------------------------------------------
# Interpreter
# ----------------------------------------------------------------------

_MAX_OPS = 1_000_000  # LoopCounter analog: hard budget per execution

_MATH = {
    "abs": abs, "max": max, "min": min, "pow": math.pow, "sqrt": math.sqrt,
    "cbrt": lambda x: math.copysign(abs(x) ** (1 / 3), x),
    "log": math.log, "log10": math.log10, "exp": math.exp,
    "floor": math.floor, "ceil": math.ceil, "round": round,
    "sin": math.sin, "cos": math.cos, "tan": math.tan, "atan": math.atan,
    "atan2": math.atan2, "asin": math.asin, "acos": math.acos,
    "toRadians": math.radians, "toDegrees": math.degrees,
    "hypot": math.hypot, "signum": lambda x: float((x > 0) - (x < 0)),
    "random": None,  # rejected below: scripts must be deterministic
}

_MATH_CONSTS = {"PI": math.pi, "E": math.e}


def _num(v):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise ScriptException(f"number expected, got [{type(v).__name__}]")
    return v


class Interpreter:
    def __init__(self, bindings: Dict[str, Any]):
        self.scopes: List[Dict[str, Any]] = [dict(bindings)]
        self.ops = 0

    # --- scope helpers ---

    def lookup(self, name: str):
        for scope in reversed(self.scopes):
            if name in scope:
                return scope[name]
        raise ScriptException(f"variable [{name}] is not defined")

    def declare(self, name: str, value):
        self.scopes[-1][name] = value

    def set_var(self, name: str, value):
        for scope in reversed(self.scopes):
            if name in scope:
                scope[name] = value
                return
        # painless allows assignment to create in current scope only via
        # decl; mirror leniently by declaring
        self.scopes[-1][name] = value

    def _tick(self):
        self.ops += 1
        if self.ops > _MAX_OPS:
            raise ScriptException(
                "script exceeded the allowed execution budget "
                "(possible infinite loop)")

    # --- statements ---

    def run(self, node) -> Any:
        try:
            self.exec_stmt(node)
        except _Return as r:
            return r.value
        return None

    def exec_stmt(self, node):
        self._tick()
        kind = node[0]
        if kind == "block":
            self.scopes.append({})
            try:
                for s in node[1]:
                    self.exec_stmt(s)
            finally:
                self.scopes.pop()
        elif kind == "decl":
            for name, val in node[1]:
                self.declare(name,
                             None if val is None else self.eval(val))
        elif kind == "expr":
            self.eval(node[1])
        elif kind == "if":
            if self.truthy(self.eval(node[1])):
                self.exec_stmt(node[2])
            elif node[3] is not None:
                self.exec_stmt(node[3])
        elif kind == "while":
            while self.truthy(self.eval(node[1])):
                self._tick()
                try:
                    self.exec_stmt(node[2])
                except _Break:
                    break
                except _Continue:
                    continue
        elif kind == "for":
            self.scopes.append({})
            try:
                if node[1] is not None:
                    self.exec_stmt(node[1])
                while node[2] is None or self.truthy(self.eval(node[2])):
                    self._tick()
                    try:
                        self.exec_stmt(node[4])
                    except _Break:
                        break
                    except _Continue:
                        pass
                    if node[3] is not None:
                        self.eval(node[3])
            finally:
                self.scopes.pop()
        elif kind == "foreach":
            it = self.eval(node[2])
            if isinstance(it, dict):
                it = list(it.keys())
            if not isinstance(it, (list, tuple, str)):
                raise ScriptException("for-each requires a list/map/string")
            self.scopes.append({})
            try:
                for v in it:
                    self._tick()
                    self.declare(node[1], v)
                    try:
                        self.exec_stmt(node[3])
                    except _Break:
                        break
                    except _Continue:
                        continue
            finally:
                self.scopes.pop()
        elif kind == "return":
            raise _Return(None if node[1] is None else self.eval(node[1]))
        elif kind == "break":
            raise _Break()
        elif kind == "continue":
            raise _Continue()
        else:
            raise ScriptException(f"unknown statement [{kind}]")

    @staticmethod
    def truthy(v) -> bool:
        if isinstance(v, bool):
            return v
        if v is None:
            return False
        raise ScriptException(
            f"condition must be boolean, got [{type(v).__name__}]")

    # --- expressions ---

    def eval(self, node) -> Any:
        self._tick()
        kind = node[0]
        if kind == "num" or kind == "str" or kind == "bool":
            return node[1]
        if kind == "null":
            return None
        if kind == "var":
            name = node[1]
            if name == "Math":
                return _MathClass
            if name in ("Integer", "Long", "Double", "Float", "String",
                        "Boolean", "Collections", "Arrays", "Objects"):
                return _StaticClass(name)
            return self.lookup(name)
        if kind == "listinit":
            return [self.eval(e) for e in node[1]]
        if kind == "mapinit":
            return {self.eval(k): self.eval(v) for k, v in node[1]}
        if kind == "strbuilder":
            return _StringBuilder()
        if kind == "cast":
            v = self.eval(node[2])
            t = node[1]
            if t in ("int", "long"):
                return int(_num(v))
            if t in ("double", "float"):
                return float(_num(v))
            if t == "String":
                return _to_string(v)
            return v
        if kind == "neg":
            return -_num(self.eval(node[1]))
        if kind == "not":
            v = self.eval(node[1])
            if not isinstance(v, bool):
                raise ScriptException("! requires a boolean")
            return not v
        if kind == "and":
            return (self.truthy(self.eval(node[1]))
                    and self.truthy(self.eval(node[2])))
        if kind == "or":
            return (self.truthy(self.eval(node[1]))
                    or self.truthy(self.eval(node[2])))
        if kind == "ternary":
            return (self.eval(node[2]) if self.truthy(self.eval(node[1]))
                    else self.eval(node[3]))
        if kind == "elvis":
            v = self.eval(node[1])
            return v if v is not None else self.eval(node[2])
        if kind == "cmp":
            return self._compare(node[1], self.eval(node[2]),
                                 self.eval(node[3]))
        if kind == "bin":
            return self._binop(node[1], self.eval(node[2]),
                               self.eval(node[3]))
        if kind == "instanceof":
            v = self.eval(node[1])
            t = node[2]
            return {
                "String": isinstance(v, str),
                "Map": isinstance(v, dict),
                "List": isinstance(v, list),
                "Integer": isinstance(v, int) and not isinstance(v, bool),
                "Long": isinstance(v, int) and not isinstance(v, bool),
                "Double": isinstance(v, float),
                "Float": isinstance(v, float),
                "Boolean": isinstance(v, bool),
            }.get(t, v is not None)
        if kind == "index":
            obj = self.eval(node[1])
            idx = self.eval(node[2])
            return self._index_get(obj, idx)
        if kind == "field" or kind == "safefield":
            obj = self.eval(node[1])
            if obj is None:
                if kind == "safefield":
                    return None
                raise ScriptException(
                    f"null pointer: cannot access [{node[2]}]")
            return self._get_field(obj, node[2])
        if kind == "call" or kind == "safecall":
            obj = self.eval(node[1])
            if obj is None:
                if kind == "safecall":
                    return None
                raise ScriptException(
                    f"null pointer: cannot call [{node[2]}]")
            args = [self.eval(a) for a in node[3]]
            return self._call_method(obj, node[2], args)
        if kind == "assign":
            return self._assign(node[1], node[2], node[3])
        if kind == "postincr":
            old = self.eval(node[2])
            self._assign(node[1], node[2], ("num", 1))
            return old
        raise ScriptException(f"unknown expression [{kind}]")

    # --- operators ---

    def _binop(self, op, a, b):
        if op == "+":
            if isinstance(a, str) or isinstance(b, str):
                return _to_string(a) + _to_string(b)
            if isinstance(a, list) and isinstance(b, list):
                return a + b
            return _num(a) + _num(b)
        if op == "-":
            return _num(a) - _num(b)
        if op == "*":
            return _num(a) * _num(b)
        if op == "/":
            a, b = _num(a), _num(b)
            if b == 0:
                if isinstance(a, int) and isinstance(b, int):
                    raise ScriptException("/ by zero")
                return math.inf if a > 0 else (-math.inf if a < 0
                                               else math.nan)
            if isinstance(a, int) and isinstance(b, int):
                q = abs(a) // abs(b)  # java truncates toward zero
                return q if (a >= 0) == (b >= 0) else -q
            return a / b
        if op == "%":
            a, b = _num(a), _num(b)
            if b == 0:
                raise ScriptException("% by zero")
            r = abs(a) % abs(b)  # java sign-of-dividend semantics
            return r if a >= 0 else -r
        raise ScriptException(f"unknown operator [{op}]")

    @staticmethod
    def _compare(op, a, b):
        if op == "==":
            return a == b
        if op == "!=":
            return a != b
        try:
            if op == "<":
                return a < b
            if op == ">":
                return a > b
            if op == "<=":
                return a <= b
            if op == ">=":
                return a >= b
        except TypeError:
            raise ScriptException(
                f"cannot compare [{type(a).__name__}] and "
                f"[{type(b).__name__}]") from None
        raise ScriptException(f"unknown comparison [{op}]")

    # --- member access / mutation ---

    @staticmethod
    def _index_get(obj, idx):
        if isinstance(obj, (DocMap, dict)):
            try:
                return obj[idx]
            except KeyError:
                return None
        if isinstance(obj, (list, str)):
            i = int(_num(idx))
            if not -len(obj) <= i < len(obj):
                raise ScriptException(f"index [{i}] out of bounds")
            return obj[i]
        raise ScriptException(
            f"cannot index [{type(obj).__name__}]")

    def _assign(self, op, target, value_node):
        value = self.eval(value_node)
        if op != "=":
            current = self.eval(target)
            value = self._binop(op[0], current, value)
        kind = target[0]
        if kind == "var":
            self.set_var(target[1], value)
        elif kind == "index":
            obj = self.eval(target[1])
            idx = self.eval(target[2])
            if isinstance(obj, dict):
                obj[idx] = value
            elif isinstance(obj, list):
                i = int(_num(idx))
                if not -len(obj) <= i < len(obj):
                    raise ScriptException(f"index [{i}] out of bounds")
                obj[i] = value
            else:
                raise ScriptException(
                    f"cannot index-assign [{type(obj).__name__}]")
        elif kind == "field":
            obj = self.eval(target[1])
            if isinstance(obj, dict):
                obj[target[2]] = value
            elif hasattr(obj, "_painless_setfield"):
                obj._painless_setfield(target[2], value)
            else:
                raise ScriptException(
                    f"cannot set field [{target[2]}] on "
                    f"[{type(obj).__name__}]")
        else:
            raise ScriptException("invalid assignment target")
        return value

    @staticmethod
    def _get_field(obj, name):
        if isinstance(obj, _MathClassType):
            if name in _MATH_CONSTS:
                return _MATH_CONSTS[name]
            raise ScriptException(f"unknown Math member [{name}]")
        if isinstance(obj, DocValues):
            if name in ("value", "values", "empty", "length"):
                return getattr(obj, name)
            raise ScriptException(f"unknown doc-values member [{name}]")
        if isinstance(obj, dict):
            return obj.get(name)
        if isinstance(obj, str) and name == "length":
            return len(obj)
        raise ScriptException(
            f"unknown field [{name}] on [{type(obj).__name__}]")

    def _call_method(self, obj, name, args):
        if isinstance(obj, _MathClassType):
            fn = _MATH.get(name)
            if fn is None:
                raise ScriptException(f"unknown Math method [{name}]")
            try:
                return fn(*[_num(a) for a in args])
            except ScriptException:
                raise
            except (ValueError, TypeError, OverflowError) as e:
                raise ScriptException(f"Math.{name}: {e}") from e
        if isinstance(obj, _StaticClass):
            return obj.call(name, args)
        table = _METHODS.get(type(obj))
        if table is not None:
            fn = table.get(name)
            if fn is not None:
                try:
                    return fn(obj, *args)
                except ScriptException:
                    raise
                except (IndexError, KeyError, ValueError, TypeError,
                        AttributeError) as e:
                    raise ScriptException(
                        f"{type(obj).__name__}.{name}: {e}") from e
        if isinstance(obj, DocValues):
            if name == "size":
                return obj.size()
            if name == "getValue":
                return obj.value
            if name == "isEmpty":
                return obj.empty
        if isinstance(obj, DocMap) and name == "containsKey":
            return obj.containsKey(args[0])
        raise ScriptException(
            f"unknown method [{name}] on [{type(obj).__name__}]")


class _MathClassType:
    pass


_MathClass = _MathClassType()


class _StaticClass:
    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def call(self, method, args):
        key = (self.name, method)
        fns = {
            ("Integer", "parseInt"): lambda s: int(s),
            ("Long", "parseLong"): lambda s: int(s),
            ("Double", "parseDouble"): lambda s: float(s),
            ("Float", "parseFloat"): lambda s: float(s),
            ("Integer", "toString"): _to_string,
            ("Double", "toString"): _to_string,
            ("String", "valueOf"): _to_string,
            ("Boolean", "parseBoolean"): lambda s: s == "true",
            ("Objects", "equals"): lambda a, b: a == b,
            ("Objects", "isNull"): lambda a: a is None,
            ("Collections", "sort"): lambda l: l.sort(),
            ("Collections", "reverse"): lambda l: l.reverse(),
            ("Collections", "max"): max,
            ("Collections", "min"): min,
            ("Arrays", "asList"): lambda *a: list(a),
        }
        fn = fns.get(key)
        if fn is None:
            raise ScriptException(
                f"unknown static method [{self.name}.{method}]")
        try:
            return fn(*args)
        except (ValueError, TypeError) as e:
            raise ScriptException(f"{self.name}.{method}: {e}") from e


def _to_string(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return f"{v:.1f}"
    return str(v)


def _substring(s, a, b=None):
    a = int(a)
    b = len(s) if b is None else int(b)
    if not (0 <= a <= b <= len(s)):
        raise ScriptException(f"substring [{a}:{b}] out of bounds")
    return s[a:b]


_METHODS: Dict[type, Dict[str, Callable]] = {
    str: {
        "length": lambda s: len(s),
        "substring": _substring,
        "contains": lambda s, x: x in s,
        "startsWith": lambda s, x: s.startswith(x),
        "endsWith": lambda s, x: s.endswith(x),
        "toLowerCase": lambda s: s.lower(),
        "toUpperCase": lambda s: s.upper(),
        "indexOf": lambda s, x, *f: s.find(x, *[int(v) for v in f]),
        "lastIndexOf": lambda s, x: s.rfind(x),
        "replace": lambda s, a, b: s.replace(a, b),
        "split": lambda s, sep: s.split(sep),
        "trim": lambda s: s.strip(),
        "charAt": lambda s, i: s[int(i)],
        "equals": lambda s, o: s == o,
        "equalsIgnoreCase": lambda s, o: isinstance(o, str)
        and s.lower() == o.lower(),
        "isEmpty": lambda s: len(s) == 0,
        "compareTo": lambda s, o: (s > o) - (s < o),
        "concat": lambda s, o: s + o,
        "toString": lambda s: s,
        "hashCode": lambda s: _java_string_hash(s),
    },
    list: {
        "add": lambda l, *a: (l.insert(int(a[0]), a[1])
                              if len(a) == 2 else l.append(a[0])) or True,
        "get": lambda l, i: l[int(i)],
        "set": lambda l, i, v: l.__setitem__(int(i), v) or v,
        "size": lambda l: len(l),
        "isEmpty": lambda l: len(l) == 0,
        "contains": lambda l, v: v in l,
        "indexOf": lambda l, v: l.index(v) if v in l else -1,
        "remove": lambda l, i: l.pop(int(i)),
        "clear": lambda l: l.clear(),
        "addAll": lambda l, o: l.extend(o) or True,
        "sort": lambda l: l.sort(),
        "toString": _to_string,
        "hashCode": lambda l: hash(tuple(map(str, l))),
    },
    dict: {
        "put": lambda m, k, v: m.update({k: v}),
        "get": lambda m, k: m.get(k),
        "getOrDefault": lambda m, k, d: m.get(k, d),
        "containsKey": lambda m, k: k in m,
        "containsValue": lambda m, v: v in m.values(),
        "remove": lambda m, k: m.pop(k, None),
        "keySet": lambda m: list(m.keys()),
        "values": lambda m: list(m.values()),
        "entrySet": lambda m: [{"key": k, "value": v}
                               for k, v in m.items()],
        "size": lambda m: len(m),
        "isEmpty": lambda m: len(m) == 0,
        "clear": lambda m: m.clear(),
        "putAll": lambda m, o: m.update(o),
    },
    _StringBuilder: {
        "append": lambda sb, v: sb.parts.append(_to_string(v)) or sb,
        "toString": lambda sb: "".join(sb.parts),
        "length": lambda sb: sum(len(p) for p in sb.parts),
    },
    int: {
        "toString": _to_string,
        "intValue": lambda v: v,
        "longValue": lambda v: v,
        "doubleValue": lambda v: float(v),
        "compareTo": lambda v, o: (v > o) - (v < o),
    },
    float: {
        "toString": _to_string,
        "intValue": lambda v: int(v),
        "longValue": lambda v: int(v),
        "doubleValue": lambda v: v,
        "isNaN": lambda v: math.isnan(v),
        "compareTo": lambda v, o: (v > o) - (v < o),
    },
}


def _java_string_hash(s: str) -> int:
    h = 0
    for c in s:
        h = (31 * h + ord(c)) & 0xFFFFFFFF
    return h - (1 << 32) if h >= (1 << 31) else h


# ----------------------------------------------------------------------
# Compiled script facade
# ----------------------------------------------------------------------


def _collect_doc_fields(node, out):
    """Fields accessed as doc['f'] — for column prefetch."""
    if not isinstance(node, tuple):
        return
    if (node[0] == "index" and node[1] == ("var", "doc")
            and node[2][0] == "str"):
        out.append(node[2][1])
    for child in node:
        if isinstance(child, tuple):
            _collect_doc_fields(child, out)
        elif isinstance(child, list):
            for c in child:
                if isinstance(c, tuple):
                    _collect_doc_fields(c, out)
                elif isinstance(c, (list, tuple)):
                    for cc in c:
                        _collect_doc_fields(cc, out)


class PainlessScript:
    """Compiled form: parsed once; each execution runs the interpreter
    over fresh bindings. API-compatible with expression.CompiledScript
    (execute / execute_columns / doc_fields) plus a generic run()."""

    def __init__(self, source: str):
        self.source = source
        try:
            self.ast = _Parser(_lex(source)).parse_program()
        except ScriptException as e:
            raise ScriptException(
                f"compile error in script [{source}]: {e}") from e
        self.doc_fields: List[str] = []
        _collect_doc_fields(self.ast, self.doc_fields)

    def run(self, bindings: Dict[str, Any]) -> Any:
        """Execute with explicit bindings (doc, ctx, params, _score...).
        The script's return value is the last `return`, or None."""
        base = {"params": {}, **bindings}
        return Interpreter(base).run(self.ast)

    # -- expression.CompiledScript compatibility --

    def execute(self, doc_values: Dict[str, float],
                params: Optional[Dict] = None, score: float = 0.0):
        def resolve(field):
            if field in doc_values:
                return [doc_values[field]]
            return []

        return self.run({
            "doc": DocMap(resolve),
            "params": dict(params or {}),
            "_score": float(score),
        })

    def execute_columns(self, columns: Dict[str, Any],
                        params: Optional[Dict] = None, scores=None):
        """Per-doc interpretation over whole-segment columns — the general
        language can't vectorize, so this loops (the numeric subset never
        reaches here: compile_script routes it to the expression engine's
        array path)."""
        import numpy as np

        sizes = [len(v) for v in columns.values()
                 if isinstance(v, np.ndarray)]
        if scores is not None:
            sizes.append(len(scores))
        if not sizes:
            return self.run({"doc": DocMap(lambda f: []),
                             "params": dict(params or {}),
                             "_score": 0.0})
        nd = min(sizes)
        out = np.zeros(nd, dtype=np.float64)
        for d in range(nd):
            def resolve(field, _d=d):
                col = columns.get(field)
                if col is None:
                    return []
                lens = columns.get(field + "#len")
                if lens is not None and float(lens[_d]) == 0.0:
                    return []
                return [float(col[_d])]

            val = self.run({
                "doc": DocMap(resolve),
                "params": dict(params or {}),
                "_score": float(scores[d]) if scores is not None else 0.0,
            })
            if isinstance(val, bool):
                out[d] = 1.0 if val else 0.0
            elif isinstance(val, (int, float)):
                out[d] = float(val)
            else:
                out[d] = 0.0
        return out


def segment_doc_resolver(segment, local_doc: int) -> Callable[[str],
                                                              List[Any]]:
    """Typed per-doc doc-values resolver: numeric fields yield floats
    (ints when integral), keyword/string fields yield their terms —
    the ScriptDocValues.Strings/Longs/Doubles split of the reference."""
    def resolve(field: str) -> List[Any]:
        col = segment.numeric_columns.get(field)
        if col is not None and col.exists[local_doc]:
            sel = col.flat_docs[: col.count] == local_doc
            out = []
            for v in col.flat_values[: col.count][sel]:
                f = float(v)
                out.append(int(f) if f.is_integer() else f)
            return out
        ocol = (segment.ordinal_columns.get(field)
                or segment.ordinal_columns.get(f"{field}.keyword"))
        if ocol is not None and ocol.exists[local_doc]:
            sel = ocol.flat_docs[: ocol.count] == local_doc
            return [ocol.terms[o]
                    for o in ocol.flat_ords[: ocol.count][sel]]
        return []

    return resolve


def execute_update_script(script: PainlessScript, source: dict,
                          params: Optional[Dict] = None,
                          doc_meta: Optional[Dict] = None) -> Tuple[dict,
                                                                    str]:
    """Scripted update (UpdateHelper.executeScripts): the script mutates
    ctx._source in place and may set ctx.op ('index' | 'none' | 'delete').
    Returns (new_source, op)."""
    ctx = {"_source": source, "op": "index", **(doc_meta or {})}
    script.run({"ctx": ctx, "params": dict(params or {})})
    op = ctx.get("op", "index")
    if op not in ("index", "none", "noop", "delete", "create"):
        raise ScriptException(f"Operation type [{op}] not allowed")
    return ctx["_source"], ("none" if op == "noop" else op)
