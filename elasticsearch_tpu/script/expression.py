"""A safe numeric expression engine — the scripting surface.

Role model: ``modules/lang-expression`` (numeric-only scripts compiled for
sort/score/fields use) and the numeric subset of Painless
(modules/lang-painless). Scripts reference doc values via ``doc['f'].value``
and parameters via ``params.name``; the expression compiles to Python
arithmetic over resolved numbers (and, for the vectorized scoring path, to
numpy column math over a whole segment).

Deliberately NOT an eval of user Python: the grammar is digits, + - * / %
( ) comparison operators, and the whitelisted function names below —
anything else is rejected at compile (the reference whitelists via
Painless's Definition for the same reason).
"""

from __future__ import annotations

import functools
import math
import re
from typing import Dict, Optional

from elasticsearch_tpu.common.errors import ParsingException

_DOC_VALUE_RE = re.compile(r"doc\[['\"]([^'\"]+)['\"]\]\.value")
_DOC_LEN_RE = re.compile(r"doc\[['\"]([^'\"]+)['\"]\]\.length")
_PARAM_RE = re.compile(r"params\.(\w+)")
_SCORE_RE = re.compile(r"\b_score\b")

_FUNCTIONS = {
    "abs": abs, "sqrt": math.sqrt, "log": math.log, "log10": math.log10,
    "exp": math.exp, "min": min, "max": max, "pow": pow, "floor": math.floor,
    "ceil": math.ceil, "round": round, "sin": math.sin, "cos": math.cos,
}

_ALLOWED = set("0123456789.+-*/()%,<>=! eE")


class CompiledScript:
    def __init__(self, source: str):
        self.source = source
        self.doc_fields = _DOC_VALUE_RE.findall(source) + _DOC_LEN_RE.findall(source)
        self._painless = None  # lazy fallback for non-numeric PARAMS

    def _painless_fallback(self):
        # a source can fit the numeric grammar while its params are
        # strings/lists at runtime (e.g. "params.label"): re-dispatch to
        # the full language instead of crashing on float()
        if self._painless is None:
            from elasticsearch_tpu.script.painless import PainlessScript

            self._painless = PainlessScript(self.source)
        return self._painless

    def execute(self, doc_values: Dict[str, float],
                params: Optional[Dict] = None, score: float = 0.0):
        expr = self.source
        expr = _DOC_VALUE_RE.sub(
            lambda m: repr(float(doc_values.get(m.group(1), 0.0))), expr
        )
        expr = _DOC_LEN_RE.sub(
            lambda m: repr(float(doc_values.get(f"{m.group(1)}#len", 0.0))), expr
        )
        expr = _SCORE_RE.sub(repr(float(score)), expr)
        for name, value in sorted((params or {}).items(), key=lambda kv: -len(kv[0])):
            if f"params.{name}" not in expr:
                continue  # unreferenced param must not force the fallback
            try:
                sub = repr(float(value))
            except (TypeError, ValueError):
                return self._painless_fallback().execute(
                    doc_values, params, score)
            expr = expr.replace(f"params.{name}", sub)
        stripped = expr
        for fn in _FUNCTIONS:
            stripped = stripped.replace(fn, "")
        if not all(c in _ALLOWED for c in stripped):
            raise ParsingException(
                f"unsupported script [{self.source}]: only numeric expressions "
                f"over doc values/params are allowed"
            )
        try:
            return eval(  # noqa: S307 — grammar-sanitized above
                expr, {"__builtins__": {}}, dict(_FUNCTIONS)
            )
        except ZeroDivisionError:
            return None
        except Exception as e:
            raise ParsingException(
                f"failed to run script [{self.source}]: {e}"
            ) from e


    def execute_columns(self, columns: Dict[str, "object"],
                        params: Optional[Dict] = None, scores=None):
        """Vectorized evaluation over whole-segment columns: doc values
        bind to numpy arrays instead of scalars (one pass, no per-doc
        loop — the XLA-friendly path for script query/filter)."""
        import numpy as np

        env = {
            "abs": np.abs, "sqrt": np.sqrt, "log": np.log, "log10": np.log10,
            "exp": np.exp, "min": np.minimum, "max": np.maximum, "pow": np.power,
            "floor": np.floor, "ceil": np.ceil, "round": np.round,
            "sin": np.sin, "cos": np.cos,
        }
        bound: Dict[str, object] = {}

        def bind(value):
            name = f"_v{len(bound)}_"
            bound[name] = value
            return name

        expr = self.source
        expr = _DOC_VALUE_RE.sub(
            lambda m: bind(columns.get(m.group(1), 0.0)), expr)
        expr = _DOC_LEN_RE.sub(
            lambda m: bind(columns.get(f"{m.group(1)}#len", 0.0)), expr)
        expr = _SCORE_RE.sub(
            lambda m: bind(scores if scores is not None else 0.0), expr)
        for name, value in sorted((params or {}).items(), key=lambda kv: -len(kv[0])):
            if f"params.{name}" not in expr:
                continue  # unreferenced param must not force the fallback
            try:
                sub = repr(float(value))
            except (TypeError, ValueError):
                return self._painless_fallback().execute_columns(
                    columns, params, scores)
            expr = expr.replace(f"params.{name}", sub)
        stripped = re.sub(r"_v\d+_", "", expr)
        for fn in _FUNCTIONS:
            stripped = stripped.replace(fn, "")
        if not all(c in _ALLOWED for c in stripped):
            raise ParsingException(
                f"unsupported script [{self.source}]: only numeric expressions "
                f"over doc values/params are allowed"
            )
        try:
            with np.errstate(divide="ignore", invalid="ignore"):
                return eval(  # noqa: S307 — grammar-sanitized above
                    expr, {"__builtins__": {}}, {**env, **bound}
                )
        except ZeroDivisionError:
            # scalar-bound division by zero: same non-match contract as
            # the per-doc execute() path
            return None
        except Exception as e:
            raise ParsingException(
                f"failed to run script [{self.source}]: {e}"
            ) from e


# ScriptPlugin extension point: {lang: compile(source) -> CompiledScript-like}
CUSTOM_SCRIPT_ENGINES: dict = {}


def expression_eligible(src: str) -> bool:
    """True when the source fits the numeric-expression grammar (the
    whole-segment array fast path). The full painless engine serves
    everything else."""
    stripped = _DOC_VALUE_RE.sub("0", src)
    stripped = _DOC_LEN_RE.sub("0", stripped)
    stripped = _SCORE_RE.sub("0", stripped)
    stripped = _PARAM_RE.sub("0", stripped)
    for fn in _FUNCTIONS:
        stripped = stripped.replace(fn, "")
    return all(c in _ALLOWED for c in stripped)


def compile_script(script_spec):
    """Accepts the reference's script spec shapes: a string, or
    {"source"|"inline": ..., "lang": ..., "params": {...}} (params bound
    at execute). Non-default langs dispatch to plugin script engines
    (ScriptService.compile — script/ScriptService.java:223).

    The default lang is painless; sources that fit the numeric expression
    grammar compile to the expression engine (vectorized whole-segment
    array math — the XLA-friendly path), everything else to the painless
    interpreter (script/painless.py). lang=expression forces the numeric
    engine and rejects anything outside its grammar at compile time."""
    if isinstance(script_spec, str):
        script_spec = {"source": script_spec}
    src = script_spec.get("source") or script_spec.get("inline")
    if src is None:
        raise ParsingException("script requires [source]")
    if not isinstance(src, str):
        raise ParsingException("script [source] must be a string")
    lang = script_spec.get("lang")
    if lang is not None and lang not in ("painless", "expression"):
        engine = CUSTOM_SCRIPT_ENGINES.get(lang)
        if engine is None:
            raise ParsingException(f"script_lang not supported [{lang}]")
        return engine(src)
    return _compile_default_lang(src, lang)


@functools.lru_cache(maxsize=512)
def _compile_default_lang(src: str, lang):
    """Compiled scripts are stateless (fresh interpreter per execution),
    so identical sources share one parse — bulk pipelines and scripted
    updates would otherwise re-lex/re-parse per document."""
    if expression_eligible(src):
        return CompiledScript(src)
    if lang == "expression":
        raise ParsingException(
            f"unsupported script [{src}]: lang=expression allows only "
            f"numeric expressions over doc values/params")
    from elasticsearch_tpu.script.painless import PainlessScript

    return PainlessScript(src)


def segment_columns(segment, doc_fields) -> Dict[str, "object"]:
    """Whole-segment column bindings for execute_columns: for each doc
    field, the first-value column under `f` and the per-doc value count
    under `f#len`; absent fields bind zero columns so expressions stay in
    array arithmetic on every segment."""
    import numpy as np

    nd = segment.nd_pad
    columns: Dict[str, object] = {}
    for f in doc_fields:
        col = segment.numeric_columns.get(f)
        if col is not None:
            columns[f] = np.where(col.exists, col.first_value, 0.0)
            lens = np.bincount(col.flat_docs[: col.count], minlength=nd + 1)
            columns[f + "#len"] = lens[:nd].astype(np.float64)
            continue
        ocol = segment.ordinal_columns.get(f) or segment.ordinal_columns.get(
            f"{f}.keyword"
        )
        if ocol is not None:
            columns[f] = np.where(ocol.exists,
                                  ocol.first_ord.astype(np.float64), 0.0)
            lens = np.bincount(ocol.flat_docs[: ocol.count], minlength=nd + 1)
            columns[f + "#len"] = lens[:nd].astype(np.float64)
        else:
            columns[f] = np.zeros(nd, dtype=np.float64)
            columns[f + "#len"] = np.zeros(nd, dtype=np.float64)
    return columns


def doc_values_for(segment, local_doc: int, fields) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for f in fields:
        col = segment.numeric_columns.get(f)
        if col is not None and col.exists[local_doc]:
            out[f] = float(col.first_value[local_doc])
            sel = col.flat_docs[: col.count] == local_doc
            out[f + "#len"] = float(sel.sum())
            continue
        ocol = segment.ordinal_columns.get(f) or segment.ordinal_columns.get(
            f"{f}.keyword"
        )
        if ocol is not None and ocol.exists[local_doc]:
            out[f] = float(ocol.first_ord[local_doc])
            out[f + "#len"] = 1.0
    return out
