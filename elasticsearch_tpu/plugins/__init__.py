"""Plugin SPI + plugin discovery/installation.

Role model: the reference's extension system (core/.../plugins/) —
``Plugin`` base class plus per-area SPIs (``SearchPlugin``,
``AnalysisPlugin``, ``MapperPlugin``, ``IngestPlugin``, ``ScriptPlugin``,
``ActionPlugin``, ``RepositoryPlugin``…) discovered by ``PluginsService``
(plugins/PluginsService.java:68) and wired into every layer through the
``Node`` constructor (node/Node.java:246-455).

Here a plugin is a Python class subclassing :class:`Plugin`; the hook
methods mirror the reference SPIs. ``PluginsService`` loads plugin classes
passed to ``Node(plugins=[...])`` or named in the ``node.plugins`` setting
as ``"module.path:ClassName"`` strings (the classpath-discovery analog)
and installs their registrations into the framework's registries.
"""

from __future__ import annotations

import importlib
from typing import Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import IllegalArgumentException


class Plugin:
    """Base plugin. Subclass and override the hooks you need; every hook
    matches a reference SPI (named in the docstring)."""

    name: str = "unnamed"
    description: str = ""
    version: str = "1.0.0"

    # -- SearchPlugin ---------------------------------------------------
    def get_queries(self) -> Dict[str, Callable]:
        """{query_name: parser(qbody) -> QueryBuilder}
        (SearchPlugin.getQueries)."""
        return {}

    def get_aggregations(self) -> Dict[str, Callable]:
        """{agg_type: run(spec, views) -> result dict}
        (SearchPlugin.getAggregations). ``spec`` is AggSpec, ``views`` the
        matched SegmentViews; the function owns compute AND reduce."""
        return {}

    # -- MapperPlugin ---------------------------------------------------
    def get_field_types(self) -> List[type]:
        """FieldType subclasses (MapperPlugin.getMappers)."""
        return []

    # -- AnalysisPlugin -------------------------------------------------
    def get_analyzers(self) -> Dict[str, object]:
        """{name: Analyzer} (AnalysisPlugin.getAnalyzers)."""
        return {}

    def get_tokenizers(self) -> Dict[str, Callable]:
        return {}

    def get_token_filters(self) -> Dict[str, Callable]:
        return {}

    def get_char_filters(self) -> Dict[str, Callable]:
        return {}

    # -- IngestPlugin ---------------------------------------------------
    def get_processors(self) -> Dict[str, Callable]:
        """{type: fn(config, doc) -> None} (IngestPlugin.getProcessors)."""
        return {}

    # -- ScriptPlugin ---------------------------------------------------
    def get_script_engines(self) -> Dict[str, Callable]:
        """{lang: compile(source) -> CompiledScript-like}
        (ScriptPlugin.getScriptEngine)."""
        return {}

    # -- ActionPlugin ---------------------------------------------------
    def get_rest_handlers(self) -> List[Tuple[str, str, Callable]]:
        """[(method, path_pattern, handler(node, req) -> (status, body))]
        (ActionPlugin.getRestHandlers)."""
        return []

    # -- RepositoryPlugin -----------------------------------------------
    def get_repositories(self) -> Dict[str, Callable]:
        """{type: factory(name, settings_dict, node) -> repository}
        (RepositoryPlugin.getRepositories)."""
        return {}

    # -- lifecycle ------------------------------------------------------
    def on_node_start(self, node) -> None:
        """Called after the node wires its services (createComponents)."""


def _load_plugin_class(spec: str) -> type:
    module_name, _, cls_name = spec.partition(":")
    if not cls_name:
        raise IllegalArgumentException(
            f"plugin [{spec}] must be 'module.path:ClassName'")
    try:
        module = importlib.import_module(module_name)
        cls = getattr(module, cls_name)
    except (ImportError, AttributeError) as e:
        raise IllegalArgumentException(
            f"Could not load plugin descriptor [{spec}]: {e}") from e
    return cls


class PluginsService:
    """Loads + installs plugins into the framework registries
    (PluginsService.java:68; registration mirrors Node ctor wiring)."""

    def __init__(self, node, settings=None, plugins: Optional[list] = None):
        self._node = node
        self.plugins: List[Plugin] = []
        for p in plugins or []:
            self.plugins.append(p() if isinstance(p, type) else p)
        for spec in (settings.get_list("node.plugins") if settings else None) or []:
            self.plugins.append(_load_plugin_class(str(spec))())
        self._installed: List[Tuple] = []  # (registry_dict, key) for removal
        self.rest_handlers: List[Tuple[str, str, Callable]] = []
        try:
            for p in self.plugins:
                self._install(p)
        except Exception:
            # roll back partial registrations: module-global registries
            # must not leak a failed node's extensions
            self.close()
            raise

    def _put(self, registry: dict, key: str, value, what: str) -> None:
        if key in registry:
            raise IllegalArgumentException(
                f"{what} [{key}] already registered, cannot register plugin twice")
        registry[key] = value
        self._installed.append((registry, key))

    def _install(self, p: Plugin) -> None:
        from elasticsearch_tpu.analysis import analyzers as A
        from elasticsearch_tpu.ingest.pipeline import PROCESSORS
        from elasticsearch_tpu.mapper.field_types import FIELD_TYPES
        from elasticsearch_tpu.script.expression import CUSTOM_SCRIPT_ENGINES
        from elasticsearch_tpu.search.aggregations import CUSTOM_AGGS
        from elasticsearch_tpu.search.query_dsl import CUSTOM_QUERY_PARSERS

        for qname, parser in p.get_queries().items():
            self._put(CUSTOM_QUERY_PARSERS, qname, parser, "query")
        for aname, fn in p.get_aggregations().items():
            self._put(CUSTOM_AGGS, aname, fn, "aggregation")
        for ft_cls in p.get_field_types():
            self._put(FIELD_TYPES, ft_cls.type_name, ft_cls, "mapper type")
        for name, a in p.get_analyzers().items():
            self._put(A.EXTRA_ANALYZERS, name, a, "analyzer")
        for name, t in p.get_tokenizers().items():
            self._put(A.EXTRA_TOKENIZERS, name, t, "tokenizer")
        for name, f in p.get_token_filters().items():
            self._put(A.EXTRA_TOKEN_FILTERS, name, f, "token_filter")
        for name, c in p.get_char_filters().items():
            self._put(A.EXTRA_CHAR_FILTERS, name, c, "char_filter")
        for ptype, fn in p.get_processors().items():
            self._put(PROCESSORS, ptype, fn, "processor")
        for lang, engine in p.get_script_engines().items():
            self._put(CUSTOM_SCRIPT_ENGINES, lang, engine, "script engine")
        for rtype, factory in p.get_repositories().items():
            self._put(self._node.snapshots.repository_types, rtype, factory,
                      "repository type")
        self.rest_handlers.extend(p.get_rest_handlers())

    def on_node_start(self) -> None:
        for p in self.plugins:
            p.on_node_start(self._node)

    def close(self) -> None:
        """Uninstall registrations (JVM unload analog; keeps module-global
        registries clean across in-process nodes, e.g. tests)."""
        for registry, key in self._installed:
            registry.pop(key, None)
        self._installed = []

    def info(self) -> List[dict]:
        return [{"name": p.name, "version": p.version,
                 "description": p.description,
                 "classname": type(p).__module__ + ":" + type(p).__name__}
                for p in self.plugins]
