"""Example plugin exercising every SPI hook.

Role model: the reference's example plugins (plugins/jvm-example,
plugins/examples/*) — small, self-contained demonstrations of each
extension point, doubling as SPI conformance fixtures for tests.
"""

from __future__ import annotations

from elasticsearch_tpu.plugins import Plugin


class ExamplePlugin(Plugin):
    """Registers one extension per SPI:

    - query ``term_prefix``: constant-score prefix match (a thin parser
      over the built-in prefix builder)
    - agg ``doc_count_times``: doc count scaled by a factor
    - field type ``reversed_keyword``: keyword stored reversed
    - analyzer component ``reverse`` token filter
    - ingest processor ``add_tag``
    - script engine ``upper`` (uppercases a source field)
    - REST handler ``GET /_example/ping``
    - repository type ``memory``
    """

    name = "example-plugin"
    description = "exercises every plugin SPI"
    version = "1.0.0"

    def get_queries(self):
        def parse_term_prefix(qbody):
            from elasticsearch_tpu.search.query_dsl import PrefixQueryBuilder

            ((field, value),) = qbody.items()
            if isinstance(value, dict):
                return PrefixQueryBuilder(field, value["value"],
                                          boost=float(value.get("boost", 1.0)))
            return PrefixQueryBuilder(field, value)

        return {"term_prefix": parse_term_prefix}

    def get_aggregations(self):
        def run_doc_count_times(spec, views):
            factor = float(spec.body.get("factor", 1.0))
            import numpy as np

            total = sum(int(np.asarray(v.mask).sum()) for v in views)
            return {"value": total * factor}

        return {"doc_count_times": run_doc_count_times}

    def get_field_types(self):
        from elasticsearch_tpu.mapper.field_types import KeywordFieldType

        class ReversedKeywordFieldType(KeywordFieldType):
            type_name = "reversed_keyword"

            def index_terms(self, value, analyzers):
                return [t[::-1] for t in
                        super().index_terms(value, analyzers)]

            def doc_value(self, value):
                return str(value)[::-1]

            def term_for_query(self, value, analyzers):
                return str(value)[::-1]

        return [ReversedKeywordFieldType]

    def get_token_filters(self):
        # token filters transform (text, start, end) tuples
        return {"reverse_example":
                lambda tokens: [(t[::-1], s, e) for t, s, e in tokens]}

    def get_processors(self):
        def add_tag(config, doc):
            tags = doc.source.setdefault(config.get("field", "tags"), [])
            tags.append(config.get("tag", "example"))

        return {"add_tag": add_tag}

    def get_script_engines(self):
        class TwiceScript:
            """Compiled-script contract: ``doc_fields`` lists the doc-value
            columns to bind; ``execute(doc_values, params, score)``."""

            def __init__(self, source):
                self.source = source
                self.doc_fields = [source]

            def execute(self, doc_values, params=None, score=0.0):
                return doc_values.get(self.source, 0.0) * 2

        return {"twice": TwiceScript}

    def get_rest_handlers(self):
        def ping(node, req):
            return 200, {"pong": True, "node": node.node_name}

        return [("GET", "/_example/ping", ping)]

    def get_repositories(self):
        class MemoryRepository:
            """In-process blob map (test double for cloud repositories)."""

            def __init__(self, name, settings, node):
                self.name = name
                self.settings = settings
                self.blobs = {}

        return {"memory": lambda name, settings, node:
                MemoryRepository(name, settings, node)}

    def on_node_start(self, node):
        self.started_on = node.node_name
