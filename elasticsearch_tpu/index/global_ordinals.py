"""Global ordinals: one ordinal space across a shard's segments.

Role model: Lucene's ``OrdinalMap`` via the reference's
``GlobalOrdinalsBuilder`` (index/fielddata/ordinals/GlobalOrdinalsBuilder
.java) and its use by ``GlobalOrdinalsStringTermsAggregator`` — built
lazily per field over the current segment set, cached until that set
changes, so cross-segment terms aggregation merges integer count arrays
instead of string dictionaries.

TPU framing: per-segment local->global maps are dense int32 arrays, and
every local ord is distinct, so a segment's per-ordinal counts fold into
the global array with one vectorized indexed add — no host string
hashing on the query path.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Sequence, Tuple

import numpy as np


class GlobalOrdinals:
    __slots__ = ("field", "terms", "_seg_maps")

    def __init__(self, field: str, terms: List[str],
                 seg_maps: Dict[int, np.ndarray]):
        self.field = field
        self.terms = terms  # global sorted unique terms; global ord = index
        self._seg_maps = seg_maps  # id(segment) -> [n_local_ords] int32

    def seg_map(self, segment) -> np.ndarray:
        return self._seg_maps[id(segment)]

    def fold_counts(self, segment, local_counts: np.ndarray,
                    out: np.ndarray) -> None:
        """Add one segment's per-local-ordinal counts into the global
        array. Local ords map to DISTINCT global ords, so a plain fancy-
        indexed add is exact (no np.add.at scatter needed)."""
        m = self.seg_map(segment)
        out[m] += local_counts[: len(m)]


_CACHE_MAX = 64
_cache: Dict[Tuple, GlobalOrdinals] = {}
_cache_lock = threading.Lock()


def _ordinal_column(segment, field: str):
    return (segment.ordinal_columns.get(field)
            or segment.ordinal_columns.get(f"{field}.keyword"))


def global_ordinals(segments: Sequence, field: str,
                    columns: Sequence = None) -> GlobalOrdinals:
    """Build (or fetch cached) global ordinals for a field over a segment
    set. The cache key includes each segment's identity and live epoch —
    refresh/merge produces new segment objects, which naturally
    invalidates (IndicesFieldDataCache semantics).

    columns: optional pre-resolved per-segment ordinal columns (the
    aggregation layer resolves text fielddata lazily — this module must
    see the SAME columns or a text field would silently map to an empty
    ordinal space)."""
    key = (field, tuple((s.name, id(s)) for s in segments))
    with _cache_lock:
        hit = _cache.get(key)
        if hit is not None:
            return hit[0]
    per_seg: List[Tuple[object, List[str]]] = []
    for i, seg in enumerate(segments):
        ocol = (columns[i] if columns is not None
                else _ordinal_column(seg, field))
        per_seg.append((seg, ocol.terms if ocol is not None else []))
    # merged global term list; per-segment map via searchsorted (each
    # segment's term list is already sorted and unique)
    all_terms = sorted(set().union(*[t for _, t in per_seg])) \
        if per_seg else []
    terms_arr = np.asarray(all_terms, dtype=object)
    seg_maps: Dict[int, np.ndarray] = {}
    for seg, terms in per_seg:
        if terms:
            seg_maps[id(seg)] = np.searchsorted(
                terms_arr, np.asarray(terms, dtype=object)).astype(np.int32)
        else:
            seg_maps[id(seg)] = np.zeros(0, np.int32)
    built = GlobalOrdinals(field, all_terms, seg_maps)

    def _evict(_ref, _key=key):
        # a cached entry must die WITH its segments: the key embeds
        # id(segment), and CPython reuses ids after free — a stale hit
        # would fold counts through the wrong local->global map
        with _cache_lock:
            _cache.pop(_key, None)

    # the weakrefs ride in the cache VALUE: they must stay alive for the
    # eviction callback to ever fire
    refs = [weakref.ref(seg, _evict) for seg in segments]
    with _cache_lock:
        if len(_cache) >= _CACHE_MAX:
            _cache.pop(next(iter(_cache)))
        _cache[key] = (built, refs)
    return built
