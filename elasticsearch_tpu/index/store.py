"""On-disk segment persistence + commit points.

Role model: ``Store`` (core/.../index/store/Store.java) + Lucene commits +
``MetaDataStateFormat`` atomic state files (gateway/MetaDataStateFormat).
A commit point is a JSON file listing the live segment set, max seqno and
tombstones, written atomically (tmp + rename). Segment payloads are
numpy ``.npz`` archives + JSON sidecars (term dictionary, sources).

Checksums: each segment directory carries a metadata file with per-array
SHA-256 digests, verified on load — the analog of Store's checksum
verification of Lucene segment files.

Corruption markers (ISSUE 16): the analog of the reference's
``Store.markStoreCorrupted`` — a detected ``CorruptIndexException``
writes a ``corrupted_*.json`` marker into the shard's store directory so
the bad copy can never be silently reused: every load path checks the
marker first and refuses. The marker is written once (the first detected
cause wins) and cleared only when a verified byte set replaces the
directory (peer-recovery file install wipes the directory; explicit
:meth:`Store.clear_corruption_markers` covers rebuild-in-place paths).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import uuid
from typing import Dict, List, Optional

import numpy as np

from elasticsearch_tpu.common.errors import ElasticsearchTpuException
from elasticsearch_tpu.index.segment import (
    GeoColumn,
    NestedContext,
    NumericColumn,
    OrdinalColumn,
    Segment,
    VectorColumn,
)


class CorruptIndexException(ElasticsearchTpuException):
    status_code = 500


MARKER_PREFIX = "corrupted_"


class Store:
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------------
    # corruption markers (Store.markStoreCorrupted parity)

    def corruption_markers(self) -> List[dict]:
        """Parsed ``corrupted_*.json`` markers, oldest first. An
        unreadable marker file still counts (an empty dict with its
        filename) — a torn marker must not unlock the copy."""
        out: List[dict] = []
        try:
            entries = sorted(os.listdir(self.directory))
        except FileNotFoundError:
            return out
        for entry in entries:
            if not (entry.startswith(MARKER_PREFIX)
                    and entry.endswith(".json")):
                continue
            p = os.path.join(self.directory, entry)
            if not os.path.isfile(p):
                continue
            try:
                with open(p, encoding="utf-8") as f:
                    marker = json.load(f)
            except (OSError, ValueError):
                marker = {}
            marker.setdefault("marker", entry)
            out.append(marker)
        return out

    def is_corrupted(self) -> bool:
        return bool(self.corruption_markers())

    def mark_corrupted(self, reason: str, site: str = "load") -> dict:
        """Write the corruption marker (once — the first cause wins) and
        return it. Idempotent: re-marking an already-marked store keeps
        the original marker so the first detected cause survives."""
        existing = self.corruption_markers()
        if existing:
            return existing[0]
        marker = {
            "marker": f"{MARKER_PREFIX}{uuid.uuid4().hex[:16]}.json",
            "reason": str(reason),
            "site": site,
            "timestamp_ms": int(time.time() * 1000),
        }
        os.makedirs(self.directory, exist_ok=True)
        tmp = os.path.join(self.directory, marker["marker"] + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(marker, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.directory, marker["marker"]))
        return marker

    def clear_corruption_markers(self) -> int:
        """Remove the markers — ONLY legal after a successful
        re-recovery installed a verified byte set (peer-recovery wipes
        the whole directory instead; this covers rebuild-in-place)."""
        cleared = 0
        for marker in self.corruption_markers():
            try:
                os.remove(os.path.join(self.directory, marker["marker"]))
                cleared += 1
            except OSError:
                pass
        return cleared

    def _check_not_corrupted(self) -> None:
        markers = self.corruption_markers()
        if markers:
            m = markers[0]
            raise CorruptIndexException(
                f"store [{self.directory}] is marked corrupted "
                f"[{m.get('marker')}]: {m.get('reason', 'unknown')} — "
                f"the copy must be re-recovered from a healthy copy, "
                f"never reloaded")

    # ------------------------------------------------------------------

    def _seg_dir(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def _commit_path(self) -> str:
        return os.path.join(self.directory, "commit.json")

    def commit(self, segments: List[Segment], max_seqno: int,
               version_map: Optional[dict] = None,
               sync_id: Optional[str] = None) -> None:
        for seg in segments:
            if not os.path.exists(self._seg_dir(seg.name)):
                self.write_segment(seg)
            # always refresh the live (tombstone) masks — cheap
            self._refresh_live(seg, self._seg_dir(seg.name))
        commit = {
            "segments": [s.name for s in segments],
            "max_seq_no": int(max_seqno),
        }
        if sync_id is not None:
            # synced-flush marker (ISSUE 14, the reference's _flush/synced
            # sync_id commit user-data): a drained shutdown stamps it so a
            # warm restart can prove the commit covers every acked op —
            # recovery is then ops-free (zero translog replay)
            commit["sync_id"] = sync_id
        if version_map is not None:
            # persist what segments cannot re-derive: delete tombstones
            # (the seqno staleness guard consults them after restart) and
            # non-default primary terms (equal-seqno tie-breaks survive
            # recovery) — reference keeps both in Lucene soft-delete docs
            commit["tombstones"] = {
                doc_id: {"seq_no": int(e.seqno), "version": int(e.version),
                         "term": int(getattr(e, "term", 1))}
                for doc_id, e in version_map.items()
                if getattr(e, "deleted", False)
            }
            commit["doc_terms"] = {
                doc_id: int(e.term)
                for doc_id, e in version_map.items()
                if not getattr(e, "deleted", False)
                and getattr(e, "term", 1) != 1
            }
        tmp = self._commit_path() + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(commit, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._commit_path())
        # garbage-collect segments dropped from the commit (post-merge)
        live_names = set(commit["segments"])
        for entry in os.listdir(self.directory):
            p = os.path.join(self.directory, entry)
            if os.path.isdir(p) and entry not in live_names:
                import shutil

                shutil.rmtree(p, ignore_errors=True)

    def _refresh_live(self, seg: Segment, d: str) -> None:
        np.save(os.path.join(d, "live.npy"), seg.live)
        for i, (_path, nctx) in enumerate(sorted(seg.nested.items())):
            self._refresh_live(nctx.segment, os.path.join(d, "nested", str(i)))

    def read_commit(self) -> Optional[dict]:
        try:
            with open(self._commit_path(), encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def load_segments(self) -> List[Segment]:
        self._check_not_corrupted()
        commit = self.read_commit()
        if commit is None:
            return []
        return [self.read_segment(name) for name in commit["segments"]]

    # ------------------------------------------------------------------

    def write_segment(self, seg: Segment) -> None:
        self._write_segment_dir(seg, self._seg_dir(seg.name))

    def _write_segment_dir(self, seg: Segment, d: str) -> None:
        os.makedirs(d, exist_ok=True)
        arrays = {
            "term_block_start": seg.term_block_start,
            "term_block_count": seg.term_block_count,
            "term_doc_freq": seg.term_doc_freq,
            "block_docs": seg.block_docs,
            "block_tfs": seg.block_tfs,
            "norms": seg.norms,
            "seqnos": seg.seqnos,
            "versions": seg.versions,
        }
        for f, col in seg.numeric_columns.items():
            arrays[f"num.{f}.flat_values"] = col.flat_values
            arrays[f"num.{f}.flat_docs"] = col.flat_docs
            arrays[f"num.{f}.first_value"] = col.first_value
            arrays[f"num.{f}.min_value"] = col.min_value
            arrays[f"num.{f}.max_value"] = col.max_value
            arrays[f"num.{f}.exists"] = col.exists
        for f, col in seg.ordinal_columns.items():
            arrays[f"ord.{f}.flat_ords"] = col.flat_ords
            arrays[f"ord.{f}.flat_docs"] = col.flat_docs
            arrays[f"ord.{f}.first_ord"] = col.first_ord
            arrays[f"ord.{f}.exists"] = col.exists
        for f, col in seg.geo_columns.items():
            arrays[f"geo.{f}.lat"] = col.lat
            arrays[f"geo.{f}.lon"] = col.lon
            arrays[f"geo.{f}.flat_docs"] = col.flat_docs
            arrays[f"geo.{f}.first_lat"] = col.first_lat
            arrays[f"geo.{f}.first_lon"] = col.first_lon
            arrays[f"geo.{f}.exists"] = col.exists
        for f, col in seg.vector_columns.items():
            # the bf16-grid f32 host mirror persists as-is: reloading it
            # reproduces the exact device bf16 staging (docs/VECTOR.md)
            arrays[f"vec.{f}.vectors"] = col.vectors
            arrays[f"vec.{f}.exists"] = col.exists
        for f, mask in seg.exists_masks.items():
            arrays[f"exists.{f}"] = mask
        np.savez(os.path.join(d, "arrays.npz"), **arrays)
        np.save(os.path.join(d, "live.npy"), seg.live)

        meta = {
            "name": seg.name,
            "num_docs": seg.num_docs,
            "term_keys": seg.term_keys,
            "field_stats": seg.field_stats,
            "field_norm_idx": seg.field_norm_idx,
            "numeric_fields": {f: c.count for f, c in seg.numeric_columns.items()},
            "ordinal_fields": {
                f: {"terms": c.terms, "count": c.count}
                for f, c in seg.ordinal_columns.items()
            },
            "geo_fields": {f: c.count for f, c in seg.geo_columns.items()},
            "vector_fields": {
                f: {"dims": c.dims, "count": c.count}
                for f, c in seg.vector_columns.items()
            },
            "doc_ids": seg.doc_ids,
            "routings": seg.routings,
            # legacy _parent values (alongside routing; rebuilds the
            # IndexService.parents registry on recovery)
            "parents": seg.parents,
            # geo_shape sidecar: raw GeoJSON/WKT per doc (geometry rebuilt
            # lazily at query time)
            "shapes": {f: {str(doc): vals for doc, vals in per_doc.items()}
                       for f, per_doc in seg.shapes.items()},
        }
        with open(os.path.join(d, "meta.json"), "w", encoding="utf-8") as f:
            json.dump(meta, f)
        with open(os.path.join(d, "sources.jsonl"), "w", encoding="utf-8") as f:
            for src in seg.sources:
                f.write(json.dumps(src, separators=(",", ":")) + "\n")
        # positions sidecar (phrase queries): term_id -> {doc: [pos...]}
        with open(os.path.join(d, "positions.json"), "w", encoding="utf-8") as f:
            json.dump(
                {str(tid): {str(doc): pos.tolist() for doc, pos in per_doc.items()}
                 for tid, per_doc in seg.positions.items()},
                f,
            )
        # nested sub-segments: one sub-directory per path, recursively
        if seg.nested:
            nd = os.path.join(d, "nested")
            os.makedirs(nd, exist_ok=True)
            index = {}
            for i, (path, nctx) in enumerate(sorted(seg.nested.items())):
                sub = os.path.join(nd, str(i))
                self._write_segment_dir(nctx.segment, sub)
                np.save(os.path.join(sub, "parent_of.npy"), nctx.parent_of)
                np.save(os.path.join(sub, "offset_of.npy"), nctx.offset_of)
                # re-checksum: the join arrays must be covered too
                self._write_checksums(sub)
                index[str(i)] = path
            with open(os.path.join(nd, "index.json"), "w", encoding="utf-8") as f:
                json.dump(index, f)
        self._write_checksums(d)

    def _write_checksums(self, d: str) -> None:
        sums = {}
        for fn in ("arrays.npz", "meta.json", "sources.jsonl", "positions.json",
                   "parent_of.npy", "offset_of.npy",
                   os.path.join("nested", "index.json")):
            p = os.path.join(d, fn)
            if not os.path.exists(p):
                continue
            with open(p, "rb") as f:
                sums[fn] = hashlib.sha256(f.read()).hexdigest()
        with open(os.path.join(d, "checksums.json"), "w", encoding="utf-8") as f:
            json.dump(sums, f)

    def verify_checksums(self, name: str) -> None:
        self._verify_checksums_dir(self._seg_dir(name))

    def verify_segment(self, name: str) -> int:
        """Re-verify a sealed segment's checksums RECURSIVELY (nested
        sub-segments included) — the background scrubber's disk pass
        (ISSUE 16). Returns the number of bytes verified; raises
        :class:`CorruptIndexException` on the first mismatch."""
        return self._verify_segment_dir(self._seg_dir(name))

    def _verify_segment_dir(self, d: str) -> int:
        self._verify_checksums_dir(d)
        total = 0
        try:
            with open(os.path.join(d, "checksums.json"),
                      encoding="utf-8") as f:
                sums = json.load(f)
            for fn in sums:
                total += os.path.getsize(os.path.join(d, fn))
        except (OSError, ValueError):
            pass  # _verify_checksums_dir already vouched for the bytes
        nested = os.path.join(d, "nested")
        if os.path.isdir(nested):
            for entry in sorted(os.listdir(nested)):
                sub = os.path.join(nested, entry)
                if os.path.isdir(sub):
                    total += self._verify_segment_dir(sub)
        return total

    def _verify_checksums_dir(self, d: str) -> None:
        try:
            with open(os.path.join(d, "checksums.json"), encoding="utf-8") as f:
                sums = json.load(f)
        except FileNotFoundError:
            raise CorruptIndexException(
                f"segment [{os.path.basename(d)}] missing checksums"
            ) from None
        except ValueError:
            # torn/truncated checksums.json: unparseable manifest is
            # corruption, not a crash — same contract as a mismatch
            raise CorruptIndexException(
                f"segment [{os.path.basename(d)}] torn checksums"
            ) from None
        for fn, expected in sums.items():
            try:
                with open(os.path.join(d, fn), "rb") as f:
                    actual = hashlib.sha256(f.read()).hexdigest()
            except FileNotFoundError:
                raise CorruptIndexException(
                    f"segment file [{os.path.basename(d)}/{fn}] listed "
                    f"in checksums but missing on disk"
                ) from None
            if actual != expected:
                raise CorruptIndexException(
                    f"checksum failed for [{os.path.basename(d)}/{fn}] "
                    f"(stored={expected[:12]}, actual={actual[:12]})"
                )

    def read_segment(self, name: str) -> Segment:
        self._check_not_corrupted()
        return self._read_segment_dir(self._seg_dir(name))

    def _read_segment_dir(self, d: str) -> Segment:
        self._verify_checksums_dir(d)
        with open(os.path.join(d, "meta.json"), encoding="utf-8") as f:
            meta = json.load(f)
        data = np.load(os.path.join(d, "arrays.npz"))
        sources = []
        with open(os.path.join(d, "sources.jsonl"), encoding="utf-8") as f:
            for line in f:
                if line.strip():
                    sources.append(json.loads(line))
        with open(os.path.join(d, "positions.json"), encoding="utf-8") as f:
            pos_raw = json.load(f)
        positions = {
            int(tid): {int(doc): np.asarray(pos, dtype=np.int32)
                       for doc, pos in per_doc.items()}
            for tid, per_doc in pos_raw.items()
        }

        numeric_columns: Dict[str, NumericColumn] = {}
        for f_name, count in meta["numeric_fields"].items():
            numeric_columns[f_name] = NumericColumn(
                data[f"num.{f_name}.flat_values"],
                data[f"num.{f_name}.flat_docs"],
                data[f"num.{f_name}.first_value"],
                data[f"num.{f_name}.min_value"],
                data[f"num.{f_name}.max_value"],
                data[f"num.{f_name}.exists"],
                count,
            )
        ordinal_columns: Dict[str, OrdinalColumn] = {}
        for f_name, info in meta["ordinal_fields"].items():
            ordinal_columns[f_name] = OrdinalColumn(
                info["terms"],
                data[f"ord.{f_name}.flat_ords"],
                data[f"ord.{f_name}.flat_docs"],
                data[f"ord.{f_name}.first_ord"],
                data[f"ord.{f_name}.exists"],
                info["count"],
            )
        geo_columns: Dict[str, GeoColumn] = {}
        for f_name, count in meta["geo_fields"].items():
            geo_columns[f_name] = GeoColumn(
                data[f"geo.{f_name}.lat"],
                data[f"geo.{f_name}.lon"],
                data[f"geo.{f_name}.flat_docs"],
                data[f"geo.{f_name}.first_lat"],
                data[f"geo.{f_name}.first_lon"],
                data[f"geo.{f_name}.exists"],
                count,
            )
        exists_masks = {
            k[len("exists."):]: data[k] for k in data.files if k.startswith("exists.")
        }
        vector_columns: Dict[str, VectorColumn] = {}
        for f_name, info in (meta.get("vector_fields") or {}).items():
            vector_columns[f_name] = VectorColumn(
                data[f"vec.{f_name}.vectors"],
                data[f"vec.{f_name}.exists"],
                int(info["dims"]),
                int(info["count"]),
            )

        seg = Segment(
            name=meta["name"],
            num_docs=meta["num_docs"],
            doc_ids=meta["doc_ids"],
            sources=sources,
            routings=meta["routings"],
            seqnos=data["seqnos"],
            versions=data["versions"],
            term_keys=meta["term_keys"],
            term_block_start=data["term_block_start"],
            term_block_count=data["term_block_count"],
            term_doc_freq=data["term_doc_freq"],
            block_docs=data["block_docs"],
            block_tfs=data["block_tfs"],
            field_stats=meta["field_stats"],
            field_norm_idx=meta["field_norm_idx"],
            norms=data["norms"],
            numeric_columns=numeric_columns,
            ordinal_columns=ordinal_columns,
            geo_columns=geo_columns,
            exists_masks=exists_masks,
            positions=positions,
            shapes={f: {int(doc): vals for doc, vals in per_doc.items()}
                    for f, per_doc in (meta.get("shapes") or {}).items()},
            parents=meta.get("parents"),
            vector_columns=vector_columns,
        )
        live_path = os.path.join(d, "live.npy")
        if os.path.exists(live_path):
            seg.live = np.load(live_path)
        nested_index = os.path.join(d, "nested", "index.json")
        if os.path.exists(nested_index):
            with open(nested_index, encoding="utf-8") as f:
                index = json.load(f)
            for i, path in index.items():
                sub = os.path.join(d, "nested", i)
                seg.nested[path] = NestedContext(
                    segment=self._read_segment_dir(sub),
                    parent_of=np.load(os.path.join(sub, "parent_of.npy")),
                    offset_of=np.load(os.path.join(sub, "offset_of.npy")),
                )
        return seg
