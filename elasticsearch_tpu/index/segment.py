"""Immutable block-packed segments — the TPU-native "Lucene segment".

Role model: a Lucene segment (postings + norms + doc values + stored
fields) as used through ``index/engine/InternalEngine.java`` and
``index/store/Store.java`` in the reference. The design is inverted for
TPU execution (SURVEY.md §7.1):

- Postings are **block-packed dense arrays**: every term's postings are
  padded to multiples of BLOCK=128 docs and laid out in one big
  ``[n_blocks, 128]`` int32 matrix (lane dimension = 128, matching the VPU
  lane width). A query gathers its terms' block rows and scores them in one
  fused program — no skip lists, no branchy iteration.
- Norms are exact float32 per-field doc-length columns (Lucene's lossy
  1-byte SmallFloat encoding is unnecessary in HBM).
- Doc values are columnar: numerics/dates as float64 CSR (value, doc)
  pairs plus a dense first-value column for sorting; keywords as ordinal
  CSR against a sorted per-field term dictionary (the reference's
  per-segment ordinals, index/fielddata/).
- Stored fields (_source) stay host-side; only ids/doc-values/postings are
  staged to device.

All shapes are padded to power-of-two buckets so XLA programs cache across
segments of similar size.
"""

from __future__ import annotations

import bisect
import itertools
import json
import logging
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.memory import (
    KIND_BOUND_TABLES,
    KIND_DOC_VALUES,
    KIND_EMBEDDINGS,
    KIND_LIVE_MASK,
    KIND_POSTINGS_PACKED,
    KIND_POSTINGS_RAW,
    KIND_SCALE_NORM,
)

BLOCK = 128  # posting block width == TPU lane count

# ledger-scope uniquifier (itertools.count.__next__ is atomic under the
# GIL): see Segment.ledger_scope
_LEDGER_SEQ = itertools.count(1)

# Field-name separator in composite term keys ("field\x1ftoken").
FIELD_SEP = "\x1f"


def next_pow2(n: int, floor: int = 1) -> int:
    p = floor
    while p < n:
        p *= 2
    return p


@dataclass
class NumericColumn:
    """CSR numeric doc values + dense sort columns (host numpy)."""

    flat_values: np.ndarray  # [n_vals] float64, padded with 0
    flat_docs: np.ndarray  # [n_vals] int32, padded with sentinel doc
    first_value: np.ndarray  # [nd_pad] float64 (first value per doc, 0 if missing)
    min_value: np.ndarray  # [nd_pad] float64 (for asc sort)
    max_value: np.ndarray  # [nd_pad] float64 (for desc sort)
    exists: np.ndarray  # [nd_pad] bool
    count: int  # real number of values


@dataclass
class OrdinalColumn:
    """String doc values as ordinals against a sorted term list."""

    terms: List[str]  # sorted unique values; ordinal = index
    flat_ords: np.ndarray  # [n_vals] int32
    flat_docs: np.ndarray  # [n_vals] int32
    first_ord: np.ndarray  # [nd_pad] int32, -1 if missing (sorts last)
    exists: np.ndarray  # [nd_pad] bool
    count: int

    def ord_of(self, term: str) -> int:
        i = bisect.bisect_left(self.terms, term)
        if i < len(self.terms) and self.terms[i] == term:
            return i
        return -1

    def ord_range(self, lo: Optional[str], hi: Optional[str],
                  include_lo: bool, include_hi: bool) -> Tuple[int, int]:
        """[lo_ord, hi_ord) half-open ordinal range for a term range query."""
        lo_ord = 0
        if lo is not None:
            lo_ord = (bisect.bisect_left(self.terms, lo) if include_lo
                      else bisect.bisect_right(self.terms, lo))
        hi_ord = len(self.terms)
        if hi is not None:
            hi_ord = (bisect.bisect_right(self.terms, hi) if include_hi
                      else bisect.bisect_left(self.terms, hi))
        return lo_ord, hi_ord


@dataclass
class VectorColumn:
    """Dense-vector doc values: one fixed-dimension embedding per doc.

    ``vectors`` is the bf16-rounded HOST mirror kept as f32 (every value
    sits exactly on the bf16 grid — what the device staging stores as
    real bf16 and the MXU kNN kernel decodes), so numpy oracles and the
    kernel score identical bits. See ops/pallas_knn.py / docs/VECTOR.md."""

    vectors: np.ndarray  # [nd_pad, dims] f32, bf16-grid values, 0 = missing
    exists: np.ndarray  # [nd_pad] bool
    dims: int
    count: int  # docs carrying a vector


@dataclass
class NestedContext:
    """A nested path's sub-segment + the join to parent docs.

    The reference interleaves nested child docs into the parent's Lucene
    block and joins with ToParentBlockJoinQuery (modules/parent-join uses
    the same machinery). The TPU-native inversion: nested objects form a
    separate dense table with an explicit ``parent_of`` pointer column;
    the child→parent join is a scatter (segment-sum) by parent id — a
    single vectorized pass instead of per-doc block walking.
    """

    segment: "Segment"  # rows = nested objects; columns keyed by full path
    parent_of: np.ndarray  # [n_objs] int32 local doc in the enclosing segment
    offset_of: np.ndarray  # [n_objs] int32 index within the parent's array


@dataclass
class GeoColumn:
    lat: np.ndarray  # [n_vals] float32
    lon: np.ndarray  # [n_vals] float32
    flat_docs: np.ndarray  # [n_vals] int32
    first_lat: np.ndarray  # [nd_pad] float32
    first_lon: np.ndarray  # [nd_pad] float32
    exists: np.ndarray  # [nd_pad] bool
    count: int


class Segment:
    """An immutable sealed segment.

    Host numpy arrays; ``device_arrays()`` stages the query-relevant subset
    to the default JAX device once and caches it (HBM staging ≙ the
    reference's filesystem page cache warming at shard open).
    """

    def __init__(
        self,
        name: str,
        num_docs: int,
        doc_ids: List[str],
        sources: List[dict],
        routings: List[Optional[str]],
        seqnos: np.ndarray,
        versions: np.ndarray,
        term_keys: List[str],
        term_block_start: np.ndarray,
        term_block_count: np.ndarray,
        term_doc_freq: np.ndarray,
        block_docs: np.ndarray,
        block_tfs: np.ndarray,
        field_stats: Dict[str, dict],
        field_norm_idx: Dict[str, int],
        norms: np.ndarray,
        numeric_columns: Dict[str, NumericColumn],
        ordinal_columns: Dict[str, OrdinalColumn],
        geo_columns: Dict[str, GeoColumn],
        exists_masks: Dict[str, np.ndarray],
        positions: Optional[Dict[int, dict]] = None,
        nested: Optional[Dict[str, NestedContext]] = None,
        shapes: Optional[Dict[str, Dict[int, list]]] = None,
        parents: Optional[List[Optional[str]]] = None,
        vector_columns: Optional[Dict[str, "VectorColumn"]] = None,
    ):
        self.name = name
        self.num_docs = num_docs
        self.nd_pad = next_pow2(max(num_docs, 1))
        self.doc_ids = doc_ids
        self.sources = sources
        self.routings = routings
        # legacy _parent metadata value per doc (None = no parent) —
        # persisted with the segment like routings (ParentFieldMapper)
        self.parents = parents if parents is not None else [None] * num_docs
        self.seqnos = seqnos
        self.versions = versions
        # sorted composite term keys; term_id = position
        self.term_keys = term_keys
        self.term_block_start = term_block_start
        self.term_block_count = term_block_count
        self.term_doc_freq = term_doc_freq
        self.block_docs = block_docs  # [n_blocks, BLOCK] int32, pad = nd_pad
        self.block_tfs = block_tfs  # [n_blocks, BLOCK] float32
        # field -> {"doc_count": int, "sum_ttf": int} for BM25 stats
        self.field_stats = field_stats
        # text field -> row in the stacked norms matrix
        self.field_norm_idx = field_norm_idx
        self.norms = norms  # [n_norm_fields, nd_pad + 1] float32, last col = 1
        self.numeric_columns = numeric_columns
        self.ordinal_columns = ordinal_columns
        self.geo_columns = geo_columns
        # dense_vector embeddings (field -> VectorColumn); staged to the
        # device lazily by ensure_vector_staged (bf16 matrix + metric
        # scale columns for the kNN planes)
        self.vector_columns = vector_columns or {}
        self.exists_masks = exists_masks  # field -> [nd_pad] bool
        # term_id -> {local_doc: np.ndarray positions} for phrase queries
        self.positions = positions or {}
        # nested path -> NestedContext (sub-segment + parent pointers)
        self.nested = nested or {}
        # geo_shape field -> {doc: [raw GeoJSON/WKT]}; geometry objects +
        # bbox tables build lazily (shape_column)
        self.shapes = shapes or {}
        self._shape_cols: Dict[str, dict] = {}
        # tombstones for deleted docs (set by the engine on update/delete)
        self.live = np.ones(self.nd_pad, dtype=bool)
        self.live[num_docs:] = False
        self._id_to_doc: Optional[Dict[str, int]] = None
        # circuit-breaker bytes charged for lazily-built per-segment
        # structures (text fielddata); released when the segment is
        # dropped (merge/close) — see release_breaker_charges()
        self.breaker_charges: Dict[str, int] = {}
        # which index owns this segment (stamped by the engine before
        # staging; the DeviceMemoryAccountant's top hierarchy level)
        self.owner_index: Optional[str] = None
        # per-OBJECT ledger scope: segment names repeat across in-process
        # cluster nodes (primary + replica copies share "idx_0_seg_N"),
        # and the accountant keys scopes by string — a shared name would
        # let one copy's register/release clobber the other's entries
        self.ledger_scope = f"{name}@{next(_LEDGER_SEQ)}"
        # how this segment's FIRST staging classifies in the lifecycle
        # event ring: a merge product carries the same logical corpus as
        # the segments it retired, so its staging is a restage
        # ("refresh" — the engine's merge path overrides this), not new
        # logical bytes; translog-replay/recovery segments stay
        # "initial" (first staging of that data in this process)
        self.stage_reason_initial = "initial"
        self._device: Optional[dict] = None
        # generic device-array cache for doc-value columns (key -> jnp array)
        self.dev_cache: Dict[str, Any] = {}
        # guards lazy per-sub live-mask staging vs delete_docs' restage
        self._live_t_lock = threading.Lock()
        # serializes COLD builds (base/kernel/vector/column stagings):
        # two queries racing a cold segment would both pay the
        # multi-second device transfer AND double-register it (the
        # second "initial" reclassifies as a restage, inflating
        # restage_amplification with zero actual restaging). Cached
        # fast paths stay lock-free; never held while taking
        # _live_t_lock, and the eviction callback
        # (release_device_staging) never takes it — so the accountant
        # lock is only ever acquired UNDER it, never the reverse
        self._device_stage_lock = threading.Lock()

    # ------------------------------------------------------------------

    @property
    def live_doc_count(self) -> int:
        return int(self.live[: self.num_docs].sum())

    def id_to_doc(self) -> Dict[str, int]:
        if self._id_to_doc is None:
            self._id_to_doc = {i: d for d, i in enumerate(self.doc_ids)}
        return self._id_to_doc

    def delete_doc(self, local_doc: int) -> None:
        self.delete_docs(np.asarray([local_doc], dtype=np.int64))

    def delete_docs(self, locals_: np.ndarray) -> None:
        if locals_.size == 0:
            return
        self.live[locals_] = False
        for nctx in self.nested.values():
            # nested objects die with their parent (Lucene deletes the
            # whole block); keeps the sub-segment's live masks consistent
            # recursively, one restage per level
            objs = np.nonzero(np.isin(nctx.parent_of, locals_))[0]
            nctx.segment.delete_docs(objs)
        dev = self._device
        if dev is not None:  # restage only the live masks
            import time as _time

            import jax.numpy as jnp

            t0 = _time.monotonic()
            dev["live"] = jnp.asarray(self.live)
            dev["live1"] = jnp.asarray(
                np.concatenate([self.live, np.zeros(1, dtype=bool)])
            )
            with self._live_t_lock:
                if "k_live_t" in dev:
                    dev["k_live_t"] = self._build_live_t_device(
                        self.kernel_geom.tile_sub)
                # per-sub variants staged by kernel_live_t_for (dense-term
                # queries that shrank the tile) restage the same way
                for key in [k for k in dev
                            if k.startswith("k_live_t_")]:
                    sub = int(key.rsplit("_", 1)[1])
                    dev[key] = self._build_live_t_device(sub)
            # the live-mask restage is the canonical delete-invalidation
            # event: the logical change is one tombstone bit per doc, the
            # restaged bytes are every dependent mask layout
            from elasticsearch_tpu.common.memory import memory_accountant

            memory_accountant().note_logical_change(
                self.owner_index or "_unassigned", int(locals_.size))
            self._account_live_masks(
                "delete_invalidation",
                duration_ms=(_time.monotonic() - t0) * 1000.0)

    def term_id(self, field_name: str, token: str) -> int:
        key = f"{field_name}{FIELD_SEP}{token}"
        i = bisect.bisect_left(self.term_keys, key)
        if i < len(self.term_keys) and self.term_keys[i] == key:
            return i
        return -1

    def terms_for_field(self, field_name: str) -> List[Tuple[str, int]]:
        """All (token, term_id) of a field, in sorted token order."""
        prefix = f"{field_name}{FIELD_SEP}"
        lo = bisect.bisect_left(self.term_keys, prefix)
        hi = bisect.bisect_left(self.term_keys, prefix + "￿")
        return [(self.term_keys[i][len(prefix):], i) for i in range(lo, hi)]

    def term_ttf(self, tid: int) -> int:
        """Total term frequency (sum of tfs over the term's postings) —
        collection stat for DFR/IB/LM similarities and DFS. Computed lazily
        from the packed tf blocks and cached."""
        cache = getattr(self, "_ttf_cache", None)
        if cache is None:
            cache = self._ttf_cache = {}
        hit = cache.get(tid)
        if hit is None:
            start = int(self.term_block_start[tid])
            cnt = int(self.term_block_count[tid])
            hit = cache[tid] = int(self.block_tfs[start:start + cnt].sum())
        return hit

    def shape_column(self, field_name: str) -> Optional[dict]:
        """Lazy geo_shape column: parsed geometry per doc + dense bbox
        table [nd_pad, 4] (min_lon, min_lat, max_lon, max_lat) for the
        vectorized prefilter. None if the field has no shapes here."""
        per_doc = self.shapes.get(field_name)
        if not per_doc:
            return None
        col = self._shape_cols.get(field_name)
        if col is None:
            from elasticsearch_tpu.utils.geometry import parse_shape

            geoms = {doc: [parse_shape(v) for v in vals]
                     for doc, vals in per_doc.items()}
            bbox = np.full((self.nd_pad, 4), np.nan, np.float64)
            exists = np.zeros(self.nd_pad, bool)
            for doc, gs in geoms.items():
                bs = [g.bbox() for g in gs]
                bbox[doc] = (min(b[0] for b in bs), min(b[1] for b in bs),
                             max(b[2] for b in bs), max(b[3] for b in bs))
                exists[doc] = True
            col = self._shape_cols[field_name] = {
                "geoms": geoms, "bbox": bbox, "exists": exists}
        return col

    def field_avgdl(self, field_name: str) -> float:
        st = self.field_stats.get(field_name)
        if not st or st["doc_count"] == 0:
            return 1.0
        return max(st["sum_ttf"] / st["doc_count"], 1.0)

    # ------------------------------------------------------------------
    # Device staging
    # ------------------------------------------------------------------

    def _account(self, kind: str, table: str, nbytes: int,
                 reason: str = "initial", duration_ms: float = 0.0) -> None:
        """Register one staged table group with the device-memory
        accountant (ISSUE 9, docs/OBSERVABILITY.md). The whole segment
        staging is one LRU-evictable scope: over HBM budget, the
        accountant drops the coldest segment's arrays (they restage
        lazily on next use)."""
        from elasticsearch_tpu.common.memory import memory_accountant

        if reason == "initial":
            # a merge product's first staging is a restage of retired
            # segments' corpus, not new logical bytes (see
            # stage_reason_initial) — without this the exact full-corpus
            # restage ROADMAP item 3 targets would land in the
            # amplification DENOMINATOR and read as ~0 amplification
            reason = self.stage_reason_initial
        memory_accountant().register(
            self.owner_index or "_unassigned", self.ledger_scope, kind,
            table,
            int(nbytes), reason=reason, duration_ms=duration_ms,
            plane="host", evict=self.release_device_staging)

    def _account_live_masks(self, reason: str,
                            duration_ms: float = 0.0) -> None:
        """(Re-)register every staged live-mask layout (live, live1,
        k_live_t, per-sub variants) — mask mutations restage all
        dependent layouts at once, one ledger entry per layout so the
        restaged-bytes accounting is exact."""
        dev = self._device
        if dev is None:
            return
        # snapshot: concurrent stagers (kernel_live_t_for, vector
        # staging) add keys to the live dict while we iterate
        for key, v in list(dev.items()):
            if key in ("live", "live1") or key.startswith("k_live_t"):
                self._account(KIND_LIVE_MASK, key, int(v.nbytes),
                              reason=reason, duration_ms=duration_ms)

    def device_arrays(self) -> dict:
        """Stage postings/norms/live-mask to the default device (cached).
        When the pallas scoring kernel is active (TPU, or interpret mode
        in tests) the kernel's tile-layout arrays ride along."""
        from elasticsearch_tpu.common.memory import memory_accountant

        # capture a LOCAL reference: a concurrent HBM-budget eviction may
        # null self._device at any point (another thread's try_reserve),
        # and an in-flight query must keep serving from the dict it
        # staged — the arrays stay alive through normal refcounting
        dev = self._device
        if dev is None:
            with self._device_stage_lock:
                dev = self._device  # a racing cold query built it
                if dev is None:
                    from elasticsearch_tpu.common.staging import run_staged

                    # transient device faults retry with bounded backoff
                    # (search.staging.retry.*); a terminal fault
                    # propagates — the base staging is MANDATORY for
                    # this shard's query phase, so the shard-failure
                    # isolation path (PR 4) owns it: partial results,
                    # never a 5xx. Nothing publishes or registers until
                    # the whole group staged (register-then-commit).
                    dev = run_staged(
                        self._stage_base_arrays,
                        index=self.owner_index or "_unassigned",
                        kind=KIND_POSTINGS_RAW, plane="host")
        else:
            memory_accountant().touch(self.owner_index or "_unassigned",
                                      self.ledger_scope)
        if "k_docs" not in dev and "k_packed" not in dev:
            # lazy: the pallas mode may turn on after the first staging
            # (ES_TPU_PALLAS flips in tests; backend selection at runtime)
            with self._device_stage_lock:
                if "k_docs" not in dev and "k_packed" not in dev:
                    from elasticsearch_tpu.common.staging import run_staged

                    try:
                        run_staged(
                            lambda: self._stage_kernel_arrays(dev),
                            index=self.owner_index or "_unassigned",
                            kind="postings", plane="host")
                    except Exception:  # noqa: BLE001 — terminal
                        # classified staging fault: the kernel tables
                        # are an OPTIONAL fast plane for the host rung —
                        # this query's segments score on the scatter
                        # engine (byte-level parity contract) and the
                        # next query retries the staging (self-heal once
                        # the fault clears; docs/RESILIENCE.md)
                        logging.getLogger(
                            "elasticsearch_tpu.index.segment").warning(
                            "[%s] kernel staging failed; segment [%s] "
                            "scores on the scatter engine this query",
                            self.owner_index or "_unassigned", self.name,
                            exc_info=True)
        return dev

    def _stage_base_arrays(self) -> dict:
        """One cold-build ATTEMPT of the base staging (under
        _device_stage_lock, inside run_staged's retry loop)."""
        import time as _time

        import jax.numpy as jnp

        from elasticsearch_tpu.testing.disruption import on_device_staging

        t0 = _time.monotonic()
        live1 = np.concatenate([self.live, np.zeros(1, dtype=bool)])
        on_device_staging(self.owner_index or "_unassigned",
                          KIND_POSTINGS_RAW, "base_postings")
        dev = {
            "block_docs": jnp.asarray(self.block_docs),
            "block_tfs": jnp.asarray(self.block_tfs),
            "norms": jnp.asarray(self.norms),
            "live": jnp.asarray(self.live),
            "live1": jnp.asarray(live1),
        }
        self._device = dev
        dur = (_time.monotonic() - t0) * 1000.0
        self._account(
            KIND_POSTINGS_RAW, "base_postings",
            self.block_docs.nbytes + self.block_tfs.nbytes,
            duration_ms=dur)
        self._account(KIND_SCALE_NORM, "norms", self.norms.nbytes)
        self._account_live_masks("initial")
        return dev

    def _stage_kernel_arrays(self, dev: dict) -> None:
        from elasticsearch_tpu.ops.aggs import _pallas_mode

        if not _pallas_mode():
            return
        import time as _time

        import jax.numpy as jnp

        from elasticsearch_tpu.common.memory import memory_accountant
        from elasticsearch_tpu.ops import pallas_scoring as psc

        # HBM budget pressure valve: a MANDATORY staging (the host rung
        # scores byte-identically to the mesh kernel only through these
        # tables) — the reservation LRU-evicts colder scopes to make
        # room but a denial never blocks it; budget DENIAL lives at the
        # optional mesh-plane staging (ladder reason hbm_budget)
        memory_accountant().try_reserve(
            self.owner_index or "_unassigned",
            self.block_docs.nbytes + self.block_tfs.nbytes,
            exclude_scope=self.ledger_scope, mandatory=True)
        t0 = _time.monotonic()
        geom = psc.tile_geometry(self.nd_pad)
        frac = self._block_frac()
        bmin, bmax = psc.block_min_max(self.block_docs, self.block_tfs,
                                       self.nd_pad)
        # postings codec (ISSUE 6, docs/PRUNING.md): "packed" stages ONE
        # bit-packed i32 word per posting instead of the (docs i32,
        # frac f32) pair — half the staged postings bytes AND half the
        # per-query posting-window DMA traffic. Preference order: the
        # per-segment stamp (engine inherits the index setting), else
        # the node default (ES_TPU_PALLAS_CODEC), demoted to raw when
        # the doc space exceeds the packed word's doc capacity.
        codec = psc.resolve_postings_codec(
            getattr(self, "postings_codec", None), self.nd_pad)
        # stage fully, then publish atomically: a concurrent search thread
        # must never observe k_docs without k_frac/k_live_t (dict.update
        # of a prebuilt dict is atomic under the GIL), and kernel_geom is
        # the eligibility signal so it is set LAST. Register-then-commit
        # (ISSUE 10): a fault anywhere before dev.update publishes
        # nothing and registers nothing — the attempt leaves no trace
        # and run_staged's retry loop re-runs it (hooks re-consulted)
        from elasticsearch_tpu.testing.disruption import on_device_staging

        kind_postings = (KIND_POSTINGS_PACKED if codec == "packed"
                         else KIND_POSTINGS_RAW)
        owner = self.owner_index or "_unassigned"
        on_device_staging(owner, KIND_LIVE_MASK, "k_live_t")
        staged = {
            "k_live_t": jnp.asarray(
                psc.build_live_t(self.live.astype(np.float32), geom)),
        }
        on_device_staging(owner, kind_postings, "k_postings")
        if codec == "packed":
            pk = psc.pack_segment_blocks(self.block_docs, frac,
                                         self.nd_pad)
            staged["k_packed"] = jnp.asarray(pk)
            postings_bytes = int(pk.nbytes)
        else:
            dp, fp = psc.pad_segment_blocks(self.block_docs, frac,
                                            self.nd_pad)
            staged["k_docs"] = jnp.asarray(dp)
            staged["k_frac"] = jnp.asarray(fp)
            postings_bytes = int(dp.nbytes + fp.nbytes)
        self.kernel_postings_bytes = postings_bytes
        self.kernel_bmin = bmin
        self.kernel_bmax = bmax
        self.kernel_codec = codec
        dev.update(staged)
        self.kernel_geom = geom
        dur = (_time.monotonic() - t0) * 1000.0
        self._account(kind_postings, "k_postings",
                      self.kernel_postings_bytes, duration_ms=dur)
        # bmin/bmax stay host-resident but scale with the plane: tracked
        # under bound_tables so the per-kind sums explain the footprint
        self._account(KIND_BOUND_TABLES, "k_bounds",
                      int(bmin.nbytes + bmax.nbytes))
        self._account(KIND_LIVE_MASK, "k_live_t",
                      int(staged["k_live_t"].nbytes), duration_ms=dur)

    def _build_live_t_device(self, sub: int):
        import jax.numpy as jnp

        from elasticsearch_tpu.ops import pallas_scoring as psc

        return jnp.asarray(psc.build_live_t(
            self.live.astype(np.float32),
            psc.tile_geometry(self.nd_pad, sub)))

    def kernel_live_t_for(self, sub: int) -> str:
        """Lazily stage the live-mask tile layout for a non-default
        tile_sub and return its device-dict key. Queries containing a
        dense (high-df) term shrink the tile so the per-tile covering
        window fits the kernel bound (see query_dsl's geometry ladder);
        docs/frac/bmin/bmax are tile-size independent, only this mask
        layout changes. Locked against delete_docs' restage so a stale
        mask can never be published after a concurrent delete."""
        key = f"k_live_t_{sub}"
        dev = self.device_arrays()  # restages if the budget evicted us
        staged_nbytes = dur = 0
        with self._live_t_lock:
            if key not in dev:
                import time as _time

                t0 = _time.monotonic()
                arr = self._build_live_t_device(sub)
                dev[key] = arr
                staged_nbytes = int(arr.nbytes)
                dur = (_time.monotonic() - t0) * 1000.0
        if staged_nbytes:
            # a shrunk tile is a geometry change: the same mask data
            # restages in a new layout (docs/OBSERVABILITY.md). Accounted
            # OUTSIDE _live_t_lock — the budget evictor holds the
            # accountant lock when it drops stagings, so taking the
            # accountant lock under _live_t_lock would invert lock order
            self._account(KIND_LIVE_MASK, key, staged_nbytes,
                          reason="geometry_change", duration_ms=dur)
        return key

    def _block_frac(self) -> np.ndarray:
        """Per-posting BM25 norm factors, computed per FIELD (each field's
        avgdl and doc-length column differ; a block belongs to exactly one
        term and thus one field)."""
        from elasticsearch_tpu.ops import pallas_scoring as psc

        frac = np.zeros_like(self.block_tfs)
        for field, row in self.field_norm_idx.items():
            prefix = f"{field}{FIELD_SEP}"
            lo = bisect.bisect_left(self.term_keys, prefix)
            hi = bisect.bisect_left(self.term_keys, prefix + "￿")
            if lo >= hi:
                continue
            b0 = int(self.term_block_start[lo])
            b1 = int(self.term_block_start[hi - 1]
                     + self.term_block_count[hi - 1])
            frac[b0:b1] = psc.compute_block_frac(
                self.block_docs[b0:b1], self.block_tfs[b0:b1],
                self.norms[row], self.field_avgdl(field))
        return frac

    def ensure_vector_staged(self, field: str, metric: str = "cosine"):
        """Lazily stage a dense_vector field's kNN arrays to the device
        and return their device-dict keys: (emb bf16 [nd_pad, d_pad],
        inverse-norm f32 [nd_pad] — the cosine scale column, staged only
        when the metric needs it, exists1 bool [nd_pad + 1]) plus the
        padded dim count, or None when no doc of this segment carries
        the field. The arrays are immutable (deletes ride the live mask
        applied outside the plan), so no restage hook is needed."""
        col = self.vector_columns.get(field)
        if col is None:
            return None
        emb_key = f"k_vec_{field}"
        norm_key = f"k_vecnorm_{field}"
        exists_key = f"k_vecexists_{field}"
        dev = self.device_arrays()  # ensure the base staging dict exists
        import jax.numpy as jnp

        from elasticsearch_tpu.ops import pallas_knn as pkn

        from elasticsearch_tpu.common.staging import run_staged

        if emb_key not in dev:
            with self._device_stage_lock:
                if emb_key not in dev:  # racing cold stager built it
                    # transient faults retry with backoff; a terminal
                    # fault propagates (the host kNN rung needs these
                    # arrays — shard-failure isolation owns it)
                    run_staged(
                        lambda: self._stage_vector_arrays(
                            dev, col, emb_key, exists_key),
                        index=self.owner_index or "_unassigned",
                        kind=KIND_EMBEDDINGS, plane="host")
        if metric == "cosine" and norm_key not in dev:
            # only cosine reads the inverse-norm column — a dot_product
            # field skips the norm pass and the staged bytes entirely
            with self._device_stage_lock:
                if norm_key not in dev:
                    def _stage_norm():
                        from elasticsearch_tpu.testing.disruption import (
                            on_device_staging,
                        )

                        on_device_staging(
                            self.owner_index or "_unassigned",
                            KIND_SCALE_NORM, norm_key)
                        inv = pkn.vector_scale_column(
                            col.vectors, "cosine")[:, 0]
                        dev[norm_key] = jnp.asarray(inv)
                        self._account(KIND_SCALE_NORM, norm_key,
                                      int(inv.nbytes))

                    run_staged(_stage_norm,
                               index=self.owner_index or "_unassigned",
                               kind=KIND_SCALE_NORM, plane="host")
        d_pad = int(dev[emb_key].shape[1])
        return emb_key, norm_key, exists_key, d_pad

    def _stage_vector_arrays(self, dev: dict, col, emb_key: str,
                             exists_key: str) -> None:
        """Cold-build a dense_vector field's embedding + exists arrays
        (called under _device_stage_lock — see its init comment)."""
        import time as _time

        import jax.numpy as jnp

        from elasticsearch_tpu.common.memory import memory_accountant
        from elasticsearch_tpu.ops import pallas_knn as pkn

        from elasticsearch_tpu.testing.disruption import on_device_staging

        t0 = _time.monotonic()
        d_pad = pkn.pad_dims(col.dims)
        # a MANDATORY staging (the host kNN rung reads it): the
        # reservation may LRU-evict colder scopes but a denial never
        # blocks it — correctness over budget (docs/OBSERVABILITY.md)
        memory_accountant().try_reserve(
            self.owner_index or "_unassigned",
            self.nd_pad * d_pad * 2, exclude_scope=self.ledger_scope,
            mandatory=True)
        on_device_staging(self.owner_index or "_unassigned",
                          KIND_EMBEDDINGS, emb_key)
        emb = np.zeros((self.nd_pad, d_pad), np.float32)
        emb[:, : col.dims] = col.vectors
        exists1 = np.zeros(self.nd_pad + 1, bool)
        exists1[: self.nd_pad] = col.exists
        # publish atomically-enough (dict.update under the GIL): a
        # concurrent reader must never see emb without its mask
        dev.update({
            emb_key: jnp.asarray(emb, jnp.bfloat16),
            exists_key: jnp.asarray(exists1),
        })
        dur = (_time.monotonic() - t0) * 1000.0
        self._account(KIND_EMBEDDINGS, emb_key,
                      int(dev[emb_key].nbytes), duration_ms=dur)
        self._account(KIND_LIVE_MASK, exists_key, exists1.nbytes,
                      duration_ms=dur)

    def device_column(self, key: str, build) -> Any:
        """Cached device staging for a doc-value array (build() -> np array)."""
        cache = self.dev_cache  # eviction rebinds; serve from our capture
        if key not in cache:
            with self._device_stage_lock:
                if key in cache:  # racing cold stager built it
                    return cache[key]

                def _stage_column():
                    import time as _time

                    import jax.numpy as jnp

                    from elasticsearch_tpu.testing.disruption import (
                        on_device_staging,
                    )

                    t0 = _time.monotonic()
                    on_device_staging(self.owner_index or "_unassigned",
                                      KIND_DOC_VALUES, f"col:{key}")
                    cache[key] = jnp.asarray(build())
                    try:
                        nbytes = int(cache[key].nbytes)
                    except (TypeError, AttributeError):
                        nbytes = 0  # non-array values (slice masks etc.)
                    if nbytes:
                        self._account(
                            KIND_DOC_VALUES, f"col:{key}", nbytes,
                            duration_ms=(_time.monotonic() - t0) * 1000.0)

                from elasticsearch_tpu.common.staging import run_staged

                # transient faults retry with backoff; a terminal fault
                # propagates (the sort/agg consumer needs the column —
                # shard-failure isolation owns it, PR 4)
                run_staged(_stage_column,
                           index=self.owner_index or "_unassigned",
                           kind=KIND_DOC_VALUES, plane="host")
        return cache[key]

    def release_device_staging(self) -> None:
        """Drop every cached device staging (HBM eviction / segment
        retirement): the arrays lazily restage on next use, so this is
        always safe — in-flight queries keep their captured references
        alive through normal refcounting. Returns the ledger for this
        segment to zero.

        Runs as the accountant's eviction callback WITH the accountant
        lock held, so it must not take _live_t_lock (kernel_live_t_for
        takes the locks in the opposite order); plain rebinds are atomic
        under the GIL and concurrent stagers hold their own reference."""
        self._device = None
        self.dev_cache = {}
        # search_stats sums this attribute for postings_bytes_staged
        self.kernel_postings_bytes = 0
        from elasticsearch_tpu.common.memory import memory_accountant

        memory_accountant().release_scope(
            self.owner_index or "_unassigned", self.ledger_scope)
        for nctx in self.nested.values():
            nctx.segment.release_device_staging()

    def release_breaker_charges(self) -> None:
        """The segment is being dropped (merge replaced it / shard close):
        give its accounted fielddata bytes back to the breaker."""
        if not self.breaker_charges:
            return
        from elasticsearch_tpu.common.breaker import (
            CircuitBreaker,
            breaker_service,
        )

        total = sum(self.breaker_charges.values())
        self.breaker_charges.clear()
        breaker_service().get_breaker(
            CircuitBreaker.FIELDDATA).add_without_breaking(-total)

    def memory_bytes(self) -> int:
        total = self.block_docs.nbytes + self.block_tfs.nbytes + self.norms.nbytes
        for c in self.numeric_columns.values():
            total += c.flat_values.nbytes + c.flat_docs.nbytes + c.first_value.nbytes
        for c in self.ordinal_columns.values():
            total += c.flat_ords.nbytes + c.flat_docs.nbytes + c.first_ord.nbytes
        for c in self.vector_columns.values():
            # device staging is bf16: half the host mirror's f32 bytes
            total += c.vectors.nbytes // 2 + c.exists.nbytes
        return total

    def stats(self) -> dict:
        return {
            "name": self.name,
            "num_docs": self.num_docs,
            "deleted_docs": self.num_docs - self.live_doc_count,
            "num_terms": len(self.term_keys),
            "num_posting_blocks": int(self.block_docs.shape[0]),
            "memory_in_bytes": self.memory_bytes(),
        }


class SegmentBuilder:
    """Accumulates parsed documents, seals into a Segment.

    Role model: Lucene's in-memory indexing buffer inside ``IndexWriter``
    as driven by ``InternalEngine.indexIntoLucene``
    (index/engine/InternalEngine.java:763). Documents are buffered as
    Python/numpy structures; ``seal()`` performs the "flush to segment":
    sort terms, block-pack postings, build columns.
    """

    def __init__(self, name: str, index_sort=None):
        self.name = name
        # index.sort.* spec [(field, order, missing, mode)] — applied as a
        # doc permutation at seal() (IndexSortConfig.java semantics)
        self.index_sort = index_sort
        self.doc_ids: List[str] = []
        self.sources: List[dict] = []
        self.routings: List[Optional[str]] = []
        self.parents: List[Optional[str]] = []
        self.seqnos: List[int] = []
        self.versions: List[int] = []
        # term_key -> list[(doc, tf)] — appended in doc order, so sorted by doc
        self.postings: Dict[str, List[Tuple[int, int]]] = {}
        # term_key -> {doc: [positions]}
        self.positions: Dict[str, Dict[int, List[int]]] = {}
        # field -> {doc: token_count}
        self.field_lengths: Dict[str, Dict[int, int]] = {}
        self.numeric_values: Dict[str, List[Tuple[int, float]]] = {}
        self.string_values: Dict[str, List[Tuple[int, str]]] = {}
        self.geo_values: Dict[str, List[Tuple[int, float, float]]] = {}
        # dense_vector field -> {doc: [dims] float list} (+ dims per field)
        self.vector_values: Dict[str, Dict[int, list]] = {}
        self.vector_dims: Dict[str, int] = {}
        # geo_shape field -> {doc: [raw GeoJSON/WKT values]}
        self.shape_values: Dict[str, Dict[int, list]] = {}
        self.field_docs: Dict[str, set] = {}
        # nested path -> {"builder": SegmentBuilder, "parent_of": [...],
        #                 "offset_of": [...]}
        self.nested_builders: Dict[str, dict] = {}

    @property
    def num_docs(self) -> int:
        return len(self.doc_ids)

    def add_document(self, parsed, seqno: int, version: int = 1,
                     parent: Optional[str] = None) -> int:
        """parsed: mapper.ParsedDocument. Returns the local doc id."""
        doc = len(self.doc_ids)
        self.doc_ids.append(parsed.doc_id)
        self.sources.append(parsed.source)
        self.routings.append(parsed.routing)
        self.parents.append(parent)
        self.seqnos.append(seqno)
        self.versions.append(version)
        for field_name, tokens in parsed.terms.items():
            self.field_lengths.setdefault(field_name, {})[doc] = len(tokens)
            self.field_docs.setdefault(field_name, set()).add(doc)
            counts: Dict[str, int] = {}
            for pos, tok in enumerate(tokens):
                counts[tok] = counts.get(tok, 0) + 1
                key = f"{field_name}{FIELD_SEP}{tok}"
                self.positions.setdefault(key, {}).setdefault(doc, []).append(pos)
            for tok, tf in counts.items():
                key = f"{field_name}{FIELD_SEP}{tok}"
                self.postings.setdefault(key, []).append((doc, tf))
        for field_name, vals in parsed.numeric_values.items():
            self.field_docs.setdefault(field_name, set()).add(doc)
            self.numeric_values.setdefault(field_name, []).extend(
                (doc, v) for v in vals
            )
        for field_name, vals in parsed.string_values.items():
            self.field_docs.setdefault(field_name, set()).add(doc)
            self.string_values.setdefault(field_name, []).extend(
                (doc, v) for v in vals
            )
        for field_name, pts in parsed.geo_values.items():
            self.field_docs.setdefault(field_name, set()).add(doc)
            self.geo_values.setdefault(field_name, []).extend(
                (doc, lat, lon) for lat, lon in pts
            )
        for field_name, vals in getattr(parsed, "shape_values", {}).items():
            self.field_docs.setdefault(field_name, set()).add(doc)
            self.shape_values.setdefault(field_name, {}).setdefault(
                doc, []).extend(vals)
        for field_name, vec in getattr(parsed, "vector_values", {}).items():
            self.field_docs.setdefault(field_name, set()).add(doc)
            self.vector_values.setdefault(field_name, {})[doc] = vec
            self.vector_dims[field_name] = len(vec)
        for field_name, pairs in getattr(parsed, "range_values", {}).items():
            # two parallel numeric columns stay aligned: both appended once
            # per value, in the same order (stable doc sort in seal())
            self.field_docs.setdefault(field_name, set()).add(doc)
            self.numeric_values.setdefault(f"{field_name}#lo", []).extend(
                (doc, lo) for lo, _ in pairs
            )
            self.numeric_values.setdefault(f"{field_name}#hi", []).extend(
                (doc, hi) for _, hi in pairs
            )
        self._add_nested(getattr(parsed, "nested", None) or {}, doc)
        return doc

    def _add_nested(self, nested: dict, root_doc: int) -> None:
        """Flatten nested (and nested-in-nested) sub-documents into
        per-path builders joined to the root doc."""
        for path, subdocs in nested.items():
            entry = self.nested_builders.setdefault(
                path,
                {"builder": SegmentBuilder(f"{self.name}#{path}"),
                 "parent_of": [], "offset_of": [],
                 "_per_parent": {}},
            )
            for sub in subdocs:
                offset = entry["_per_parent"].get(root_doc, 0)
                entry["_per_parent"][root_doc] = offset + 1
                # the sub-builder keeps the inner nested docs too (via its
                # own add_document recursion): relative joins for
                # nested-in-nested queries/aggs...
                inner = getattr(sub, "nested", None)
                entry["builder"].add_document(sub, seqno=-1)
                entry["parent_of"].append(root_doc)
                entry["offset_of"].append(offset)
                # ...while ALSO flattening them to the root doc, so a
                # root-level nested path "a.b" query/agg works directly
                if inner:
                    self._add_nested(inner, root_doc)

    # ------------------------------------------------------------------

    def _remap_docs(self, perm: np.ndarray) -> np.ndarray:
        """Reorder documents by ``perm`` (new position -> old doc),
        rewriting every doc-id reference so doc order becomes sort order.
        Returns the old->new map (callers holding pre-seal local doc ids —
        version map, buffered deletes — must translate through it)."""
        inv = np.empty(len(perm), np.int64)
        inv[perm] = np.arange(len(perm))  # old doc -> new doc

        def reorder(lst):
            return [lst[p] for p in perm]

        self.doc_ids = reorder(self.doc_ids)
        self.sources = reorder(self.sources)
        self.routings = reorder(self.routings)
        self.parents = reorder(self.parents)
        self.seqnos = reorder(self.seqnos)
        self.versions = reorder(self.versions)
        self.postings = {
            k: sorted((int(inv[d]), tf) for d, tf in plist)
            for k, plist in self.postings.items()
        }
        self.positions = {
            k: {int(inv[d]): pos for d, pos in per_doc.items()}
            for k, per_doc in self.positions.items()
        }
        self.field_lengths = {
            f: {int(inv[d]): ln for d, ln in per_doc.items()}
            for f, per_doc in self.field_lengths.items()
        }
        # stable doc sort keeps multi-value order (and #lo/#hi alignment)
        for store in (self.numeric_values, self.string_values):
            for f, vals in store.items():
                store[f] = sorted(
                    ((int(inv[d]),) + tuple(rest) for d, *rest in vals),
                    key=lambda t: t[0],
                )
        for f, vals in self.geo_values.items():
            self.geo_values[f] = sorted(
                ((int(inv[d]), lat, lon) for d, lat, lon in vals),
                key=lambda t: t[0],
            )
        self.field_docs = {
            f: {int(inv[d]) for d in docs} for f, docs in self.field_docs.items()
        }
        self.shape_values = {
            f: {int(inv[d]): vals for d, vals in per_doc.items()}
            for f, per_doc in self.shape_values.items()
        }
        self.vector_values = {
            f: {int(inv[d]): vec for d, vec in per_doc.items()}
            for f, per_doc in self.vector_values.items()
        }
        for entry in self.nested_builders.values():
            entry["parent_of"] = [int(inv[d]) for d in entry["parent_of"]]
        return inv

    def seal(self) -> Segment:
        # old->new doc map when an index sort permuted this segment
        self.seal_doc_remap = None
        if self.index_sort and self.num_docs > 1:
            from elasticsearch_tpu.index.index_sort import index_sort_permutation

            perm = index_sort_permutation(self, self.index_sort)
            if perm is not None:
                self.seal_doc_remap = self._remap_docs(perm)
        nd = self.num_docs
        nd_pad = next_pow2(max(nd, 1))
        term_keys = sorted(self.postings.keys())
        term_ids = {k: i for i, k in enumerate(term_keys)}

        # --- block-pack postings ---
        n_terms = len(term_keys)
        term_block_start = np.zeros(n_terms, dtype=np.int32)
        term_block_count = np.zeros(n_terms, dtype=np.int32)
        term_doc_freq = np.zeros(n_terms, dtype=np.int32)
        total_blocks = sum(
            (len(p) + BLOCK - 1) // BLOCK for p in self.postings.values()
        )
        total_blocks = max(total_blocks, 1)
        block_docs = np.full((total_blocks, BLOCK), nd_pad, dtype=np.int32)
        block_tfs = np.zeros((total_blocks, BLOCK), dtype=np.float32)
        b = 0
        for key in term_keys:
            plist = self.postings[key]
            tid = term_ids[key]
            term_doc_freq[tid] = len(plist)
            term_block_start[tid] = b
            nblocks = (len(plist) + BLOCK - 1) // BLOCK
            term_block_count[tid] = nblocks
            docs = np.fromiter((d for d, _ in plist), dtype=np.int32, count=len(plist))
            tfs = np.fromiter((t for _, t in plist), dtype=np.float32, count=len(plist))
            for i in range(nblocks):
                chunk = docs[i * BLOCK : (i + 1) * BLOCK]
                block_docs[b, : len(chunk)] = chunk
                block_tfs[b, : len(chunk)] = tfs[i * BLOCK : (i + 1) * BLOCK]
                b += 1

        # --- norms (per text field doc-length columns) ---
        field_norm_idx = {f: i for i, f in enumerate(sorted(self.field_lengths))}
        norms = np.ones((max(len(field_norm_idx), 1), nd_pad + 1), dtype=np.float32)
        field_stats: Dict[str, dict] = {}
        for f, idx in field_norm_idx.items():
            lengths = self.field_lengths[f]
            col = np.zeros(nd_pad + 1, dtype=np.float32)
            for doc, ln in lengths.items():
                col[doc] = ln
            col[nd_pad] = 1.0
            norms[idx] = col
            field_stats[f] = {
                "doc_count": len(lengths),
                "sum_ttf": int(sum(lengths.values())),
            }

        # --- numeric columns ---
        numeric_columns = {}
        for f, pairs in self.numeric_values.items():
            pairs.sort(key=lambda p: p[0])
            n_vals = len(pairs)
            cap = next_pow2(max(n_vals, 1))
            flat_docs = np.full(cap, nd_pad, dtype=np.int32)
            flat_values = np.zeros(cap, dtype=np.float64)
            first_value = np.zeros(nd_pad, dtype=np.float64)
            min_value = np.full(nd_pad, np.inf, dtype=np.float64)
            max_value = np.full(nd_pad, -np.inf, dtype=np.float64)
            exists = np.zeros(nd_pad, dtype=bool)
            for i, (doc, v) in enumerate(pairs):
                flat_docs[i] = doc
                flat_values[i] = v
                if not exists[doc]:
                    first_value[doc] = v
                exists[doc] = True
                min_value[doc] = min(min_value[doc], v)
                max_value[doc] = max(max_value[doc], v)
            numeric_columns[f] = NumericColumn(
                flat_values, flat_docs, first_value, min_value, max_value, exists, n_vals
            )

        # --- ordinal (string) columns ---
        ordinal_columns = {}
        for f, pairs in self.string_values.items():
            # dedupe (doc, value): SortedSetDocValues semantics — a doc holds
            # each distinct value once, in value order (first_ord must be
            # the doc's MIN ordinal: sort keys + early termination rely on
            # it being deterministic)
            pairs = sorted(set(pairs))
            terms = sorted({v for _, v in pairs})
            ord_map = {t: i for i, t in enumerate(terms)}
            n_vals = len(pairs)
            cap = next_pow2(max(n_vals, 1))
            flat_docs = np.full(cap, nd_pad, dtype=np.int32)
            flat_ords = np.zeros(cap, dtype=np.int32)
            first_ord = np.full(nd_pad, -1, dtype=np.int32)
            exists = np.zeros(nd_pad, dtype=bool)
            for i, (doc, v) in enumerate(pairs):
                flat_docs[i] = doc
                flat_ords[i] = ord_map[v]
                if first_ord[doc] < 0:
                    first_ord[doc] = ord_map[v]
                exists[doc] = True
            ordinal_columns[f] = OrdinalColumn(
                terms, flat_ords, flat_docs, first_ord, exists, n_vals
            )

        # --- geo columns ---
        geo_columns = {}
        for f, triples in self.geo_values.items():
            triples.sort(key=lambda p: p[0])
            n_vals = len(triples)
            cap = next_pow2(max(n_vals, 1))
            flat_docs = np.full(cap, nd_pad, dtype=np.int32)
            lat = np.zeros(cap, dtype=np.float32)
            lon = np.zeros(cap, dtype=np.float32)
            first_lat = np.zeros(nd_pad, dtype=np.float32)
            first_lon = np.zeros(nd_pad, dtype=np.float32)
            exists = np.zeros(nd_pad, dtype=bool)
            for i, (doc, la, lo) in enumerate(triples):
                flat_docs[i] = doc
                lat[i], lon[i] = la, lo
                if not exists[doc]:
                    first_lat[doc], first_lon[doc] = la, lo
                exists[doc] = True
            geo_columns[f] = GeoColumn(lat, lon, flat_docs, first_lat, first_lon,
                                       exists, n_vals)

        # --- dense_vector columns ---
        vector_columns: Dict[str, VectorColumn] = {}
        if self.vector_values:
            from elasticsearch_tpu.ops.pallas_knn import bf16_round

            for f, per_doc in self.vector_values.items():
                dims = self.vector_dims[f]
                vecs = np.zeros((nd_pad, dims), np.float32)
                exists = np.zeros(nd_pad, dtype=bool)
                for doc, vec in per_doc.items():
                    vecs[doc] = vec
                    exists[doc] = True
                # round to the bf16 grid ONCE at seal: the host mirror,
                # the numpy oracle and the device bf16 staging all see
                # the same values (docs/VECTOR.md storage contract)
                vector_columns[f] = VectorColumn(
                    bf16_round(vecs), exists, dims, len(per_doc))

        # --- exists masks ---
        exists_masks = {}
        for f, docs in self.field_docs.items():
            mask = np.zeros(nd_pad, dtype=bool)
            for d in docs:
                mask[d] = True
            exists_masks[f] = mask

        # --- positions (host-side, for phrase queries) ---
        positions = {}
        for key, per_doc in self.positions.items():
            positions[term_ids[key]] = {
                doc: np.asarray(pos, dtype=np.int32) for doc, pos in per_doc.items()
            }

        # --- nested sub-segments ---
        nested: Dict[str, NestedContext] = {}
        for path, entry in self.nested_builders.items():
            nested[path] = NestedContext(
                segment=entry["builder"].seal(),
                parent_of=np.asarray(entry["parent_of"], dtype=np.int32),
                offset_of=np.asarray(entry["offset_of"], dtype=np.int32),
            )

        return Segment(
            name=self.name,
            num_docs=nd,
            doc_ids=list(self.doc_ids),
            sources=list(self.sources),
            routings=list(self.routings),
            seqnos=np.asarray(self.seqnos, dtype=np.int64),
            versions=np.asarray(self.versions, dtype=np.int64),
            term_keys=term_keys,
            term_block_start=term_block_start,
            term_block_count=term_block_count,
            term_doc_freq=term_doc_freq,
            block_docs=block_docs,
            block_tfs=block_tfs,
            field_stats=field_stats,
            field_norm_idx=field_norm_idx,
            norms=norms,
            numeric_columns=numeric_columns,
            ordinal_columns=ordinal_columns,
            geo_columns=geo_columns,
            exists_masks=exists_masks,
            positions=positions,
            nested=nested,
            shapes={f: dict(per_doc) for f, per_doc in self.shape_values.items()},
            parents=list(self.parents),
            vector_columns=vector_columns,
        )


class PinnedSegmentView:
    """Point-in-time view of a sealed segment — the pinned-searcher /
    ScrollContext analog (reference: search/internal/ScrollContext.java,
    SearchService.java:874 keep-alive contexts). Shares every immutable
    array (postings, doc values, stored sources, device stagings) with
    the live segment, but freezes the LIVE MASK at construction:
    concurrent deletes/updates mutate ``Segment.live`` in place and
    merges swap the engine's segment list, yet an open scroll keeps
    seeing exactly the docs that were visible when it opened. Dropping
    the view (clear_scroll / keep-alive expiry) releases the pin — plain
    refcounting via the Python references the view holds."""

    def __init__(self, seg: "Segment"):
        self._seg = seg
        self.live = seg.live.copy()
        self._pin_device: dict = {}
        # device_arrays() must return the SAME dict object every call and
        # mutate it in place when kernel_live_t_for stages a new layout —
        # ShardSearcher.query captures the dict before plan build, and a
        # PallasScoreTermsNode emitted later reads its live_key from that
        # captured snapshot (the Segment._device contract)
        self._merged: dict = {}

    def __getattr__(self, name):
        return getattr(self._seg, name)

    @property
    def live_doc_count(self) -> int:
        return int(self.live[: self._seg.num_docs].sum())

    def device_arrays(self) -> dict:
        base = self._seg.device_arrays()
        if "live1" not in self._pin_device:
            import jax.numpy as jnp

            live1 = np.concatenate([self.live, np.zeros(1, dtype=bool)])
            self._pin_device["live"] = jnp.asarray(self.live)
            self._pin_device["live1"] = jnp.asarray(live1)
        if (("k_docs" in base or "k_packed" in base)
                and "k_live_t" not in self._pin_device):
            self._pin_device["k_live_t"] = self._build_pinned_live_t(
                self._seg.kernel_geom.tile_sub)
        # shared immutable arrays come from the live segment; every
        # (mutable) live-mask entry — including per-sub variants the live
        # segment restages after deletes — comes ONLY from the pin
        for key, val in base.items():
            if key in ("live", "live1") or key.startswith("k_live_t"):
                continue
            self._merged[key] = val
        self._merged.update(self._pin_device)
        return self._merged

    def kernel_live_t_for(self, sub: int) -> str:
        key = f"k_live_t_{sub}"
        if key not in self._pin_device:
            self._pin_device[key] = self._build_pinned_live_t(sub)
            self._merged[key] = self._pin_device[key]
        return key

    def ensure_vector_staged(self, field: str, metric: str = "cosine"):
        """Vector stagings are immutable (the pin only freezes the live
        mask), so the view shares the live segment's arrays — but they
        must be copied into the view's merged dict, which a plan built
        AFTER device_arrays() was captured reads from."""
        keys = self._seg.ensure_vector_staged(field, metric)
        if keys is not None:
            base = self._seg.device_arrays()
            for key in keys[:3]:
                if key in base:
                    self._merged[key] = base[key]
        return keys

    def _build_pinned_live_t(self, sub: int):
        import jax.numpy as jnp

        from elasticsearch_tpu.ops import pallas_scoring as psc

        geom = psc.tile_geometry(self._seg.nd_pad, sub)
        return jnp.asarray(psc.build_live_t(
            self.live[: self._seg.nd_pad].astype(np.float32), geom))
