"""Sequence-number machinery: global checkpoint tracking.

Role model: ``GlobalCheckpointTracker`` (reference:
core/src/main/java/org/elasticsearch/index/seqno/GlobalCheckpointTracker.java:51)
— the primary tracks every in-sync copy's local checkpoint (highest seqno
below which all ops are processed); the global checkpoint is the minimum
over the in-sync set and fences ops-based recovery + translog trimming.
Local checkpoints are contiguous by construction here (single-writer
engine), matching ``LocalCheckpointTracker``'s invariant.
"""

from __future__ import annotations

from typing import Dict

UNASSIGNED_SEQ_NO = -2
NO_OPS_PERFORMED = -1


class GlobalCheckpointTracker:
    """Primary-side tracker of per-copy local checkpoints."""

    def __init__(self, primary_id: str):
        self.primary_id = primary_id
        # copy id (node/allocation id) -> last reported local checkpoint
        self.local_checkpoints: Dict[str, int] = {primary_id: NO_OPS_PERFORMED}
        self.in_sync: set = {primary_id}
        # copies that finished recovery but whose checkpoint is still below
        # the global checkpoint (reference: pendingInSync — membership is
        # deferred so the global checkpoint stays monotonic)
        self.pending_in_sync: set = set()
        self._gcp_floor = NO_OPS_PERFORMED

    def seed_global_checkpoint(self, value: int) -> None:
        """Primary promotion: the new primary already learned a global
        checkpoint while it was a replica (piggybacked on writes); the
        monotonic floor starts there so the first post-promotion write
        cannot regress it."""
        if value > self._gcp_floor:
            self._gcp_floor = value

    def initiate_tracking(self, copy_id: str) -> None:
        """A recovering copy is tracked but not yet in-sync (its
        checkpoint cannot hold back the global checkpoint)."""
        self.local_checkpoints.setdefault(copy_id, NO_OPS_PERFORMED)

    def mark_in_sync(self, copy_id: str, local_checkpoint: int,
                     force: bool = False) -> None:
        """Recovery finalize: the copy caught up to the primary
        (RecoverySourceHandler finalize -> markAllocationIdAsInSync).
        If the copy is still below the current global checkpoint its
        membership is deferred (pendingInSync) until it catches up, so
        the global checkpoint never moves backwards. ``force`` is the
        primary-promotion path: routing-table copies whose checkpoints
        are unknown join the in-sync set immediately (on a fresh tracker
        the monotonic floor is still NO_OPS_PERFORMED, so this keeps the
        global checkpoint conservative rather than moving it back)."""
        prev = self.local_checkpoints.get(copy_id, NO_OPS_PERFORMED)
        self.local_checkpoints[copy_id] = max(prev, local_checkpoint)
        if force or self.local_checkpoints[copy_id] >= self.global_checkpoint:
            self.pending_in_sync.discard(copy_id)
            self.in_sync.add(copy_id)
        else:
            self.pending_in_sync.add(copy_id)

    def update_local_checkpoint(self, copy_id: str, checkpoint: int) -> None:
        prev = self.local_checkpoints.get(copy_id, NO_OPS_PERFORMED)
        self.local_checkpoints[copy_id] = max(prev, checkpoint)
        if (copy_id in self.pending_in_sync
                and self.local_checkpoints[copy_id] >= self.global_checkpoint):
            self.pending_in_sync.discard(copy_id)
            self.in_sync.add(copy_id)

    def remove(self, copy_id: str) -> None:
        """Copy failed/left: it no longer holds back the global checkpoint
        (in-sync set shrink, IndexMetaData in-sync allocation update)."""
        if copy_id != self.primary_id:
            self.local_checkpoints.pop(copy_id, None)
            self.in_sync.discard(copy_id)
            self.pending_in_sync.discard(copy_id)

    @property
    def global_checkpoint(self) -> int:
        """min local checkpoint over the in-sync set, clamped monotonic."""
        vals = [self.local_checkpoints.get(c, NO_OPS_PERFORMED)
                for c in self.in_sync]
        gcp = min(vals) if vals else NO_OPS_PERFORMED
        if gcp > self._gcp_floor:
            self._gcp_floor = gcp
        return self._gcp_floor

    def prune(self, valid_copy_ids) -> None:
        """Drop tracked copies no longer in the routing table (the
        reference recomputes membership from IndexMetaData's in-sync
        allocation ids on every cluster-state change) — a departed copy
        must not pin the global checkpoint forever."""
        for copy_id in list(self.local_checkpoints):
            if copy_id != self.primary_id and copy_id not in valid_copy_ids:
                self.remove(copy_id)

    def stats(self) -> dict:
        return {
            "global_checkpoint": self.global_checkpoint,
            "in_sync": sorted(self.in_sync),
            "local_checkpoints": dict(self.local_checkpoints),
        }


def check_active_shards(wanted, active: int, total_copies: int,
                        label: str) -> None:
    """Shared wait_for_active_shards gate (ActiveShardsObserver): resolves
    'all'/int and raises UnavailableShardsException when unmet."""
    from elasticsearch_tpu.common.errors import (
        IllegalArgumentException,
        UnavailableShardsException,
    )

    if wanted == "all":
        required = total_copies
    else:
        try:
            required = int(wanted)
        except (TypeError, ValueError):
            raise IllegalArgumentException(
                f"cannot parse wait_for_active_shards[{wanted}]") from None
    if active < required:
        raise UnavailableShardsException(
            f"{label} Not enough active copies to meet shard count of "
            f"[{wanted}] (have {active}, needed {required})")
